"""Slot-based KV cache.

A fixed buffer [num_layers, num_slots, max_len, kv_heads, head_dim] per of
K and V. Slots are the continuous-batching unit: a request owns one slot
from prefill-insert to completion. Static shapes keep the decode graph
compiled once; slot bookkeeping (free list) is host-side Python, outside jit.

Sharding: slots on `dp`, kv_heads on `tp` — within a slice the cache is
sharded exactly like the attention heads so decode attention needs no
cross-device traffic beyond the existing TP collectives.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from kubeai_tpu.parallel import sharding as sh


@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [NL, slots, max_len, KVH, D]
    v: jax.Array  # [NL, slots, max_len, KVH, D]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @staticmethod
    def logical_axes() -> tuple:
        return (None, sh.KV_SLOTS, None, sh.KV_HEADS, None)

    @staticmethod
    def create(
        num_layers: int,
        num_slots: int,
        max_len: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        sharding=None,
    ) -> "KVCache":
        shape = (num_layers, num_slots, max_len, kv_heads, head_dim)
        if sharding is not None:
            zeros = jax.jit(
                partial(jnp.zeros, shape, dtype), out_shardings=sharding
            )
            return KVCache(k=zeros(), v=zeros())
        return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


jax.tree_util.register_dataclass(KVCache, ["k", "v"], [])


def insert_sequence(
    cache_k: jax.Array,  # [NL, slots, max_len, KVH, D]
    cache_v: jax.Array,
    k_new: jax.Array,  # [NL, S, KVH, D] (one sequence, padded to S)
    v_new: jax.Array,
    slot: jax.Array,  # scalar int32
) -> tuple[jax.Array, jax.Array]:
    """Write a prefilled sequence's KV into a slot (positions 0..S-1).

    S is a padded bucket length ≤ max_len; padded tail positions hold
    garbage but are masked by the per-slot length at attention time.
    """
    start = (jnp.zeros((), jnp.int32), slot, jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
    k_new = k_new[:, None]  # [NL, 1, S, KVH, D]
    v_new = v_new[:, None]
    cache_k = jax.lax.dynamic_update_slice(cache_k, k_new.astype(cache_k.dtype), start)
    cache_v = jax.lax.dynamic_update_slice(cache_v, v_new.astype(cache_v.dtype), start)
    return cache_k, cache_v
