"""Speech-to-text serving front (SpeechToText feature).

OpenAI-compatible `/v1/audio/transcriptions` (multipart/form-data file
upload) + health/metrics — the in-tree replacement for the FasterWhisper
Pods the reference launches (reference: internal/modelcontroller/
engine_fasterwhisper.go; API surface reference: internal/openaiserver/
handler.go:38-42 routes audio/transcriptions).
"""

from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler

from kubeai_tpu.httpserver import DeepBacklogHTTPServer


import numpy as np

from kubeai_tpu.metrics.registry import Counter, Registry
from kubeai_tpu.models import whisper

logger = logging.getLogger(__name__)

_BOUNDARY_RE = re.compile(r'boundary="?([^";]+)"?')


def parse_multipart(body: bytes, content_type: str) -> dict[str, bytes]:
    m = _BOUNDARY_RE.search(content_type)
    if not m:
        raise ValueError("missing multipart boundary")
    boundary = b"--" + m.group(1).encode()
    fields: dict[str, bytes] = {}
    for part in body.split(boundary):
        if b"\r\n\r\n" not in part:
            continue
        headers, payload = part.split(b"\r\n\r\n", 1)
        name_m = re.search(rb'name="([^"]+)"', headers)
        if not name_m:
            continue
        fields[name_m.group(1).decode()] = payload.rstrip(b"\r\n-")
    return fields


class TranscriptionServer:
    def __init__(
        self,
        params,
        cfg: whisper.WhisperConfig,
        served_model_name: str,
        tokenizer=None,  # HF tokenizer for detokenization; None = ids as str
        host: str = "0.0.0.0",
        port: int = 8000,
        forced_tokens: tuple[int, ...] = (),
        max_mel_frames: int | None = None,
    ):
        self.params = params
        self.cfg = cfg
        self.served_model_name = served_model_name
        self.tokenizer = tokenizer
        self.forced_tokens = forced_tokens
        self.max_mel_frames = max_mel_frames or cfg.max_source_positions * 2
        self.registry = Registry()
        self.requests_total = Counter(
            "kubeai_engine_requests_total", "Requests served.", self.registry
        )
        self.audio_seconds = Counter(
            "kubeai_engine_audio_seconds_total",
            "Seconds of audio transcribed.",
            self.registry,
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/health":
                    return self._json(200, {"status": "ok"})
                if path == "/metrics":
                    body = outer.registry.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/v1/models":
                    return self._json(
                        200,
                        {
                            "object": "list",
                            "data": [
                                {
                                    "id": outer.served_model_name,
                                    "object": "model",
                                    "owned_by": "kubeai-tpu",
                                }
                            ],
                        },
                    )
                self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                path = self.path.split("?")[0]
                if path != "/v1/audio/transcriptions":
                    return self._json(404, {"error": {"message": "not found"}})
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n else b""
                try:
                    fields = parse_multipart(
                        body, self.headers.get("Content-Type", "")
                    )
                except ValueError as e:
                    return self._json(400, {"error": {"message": str(e)}})
                if "file" not in fields:
                    return self._json(
                        400, {"error": {"message": "missing 'file' form field"}}
                    )
                try:
                    text = outer.transcribe(fields["file"])
                except Exception as e:
                    logger.exception("transcription failed")
                    return self._json(400, {"error": {"message": str(e)}})
                self._json(200, {"text": text})

        self.httpd = DeepBacklogHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    @property
    def port(self):
        return self.httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def transcribe(self, wav_bytes: bytes) -> str:
        pcm = whisper.decode_wav(wav_bytes)
        self.requests_total.inc(model=self.served_model_name)
        self.audio_seconds.inc(len(pcm) / whisper.SAMPLE_RATE)
        mel = whisper.log_mel_spectrogram(
            pcm, n_mels=self.cfg.num_mel_bins, max_frames=self.max_mel_frames
        )
        with self._lock:  # one transcription at a time per replica
            ids = whisper.transcribe_tokens(
                self.params, self.cfg, mel, forced_tokens=self.forced_tokens
            )
        if self.tokenizer is not None:
            return self.tokenizer.decode(ids, skip_special_tokens=True)
        return " ".join(str(i) for i in ids)
