"""The TPU serving engine — the component the reference outsources to vLLM.

JetStream-style design: a fixed pool of decode *slots*, per-request prefill
that inserts KV into a slot, and a single batched decode step over all
active slots (continuous batching). Everything jitted with static shapes;
prompt lengths are bucketed to bound recompilation.

Reference seams this replaces:
  - the vLLM serving container (reference: internal/modelcontroller/engine_vllm.go)
  - the vLLM admin client for LoRA (reference: internal/vllmclient/client.go)
"""

from kubeai_tpu.engine.kvcache import KVCache
from kubeai_tpu.engine.engine import Engine, EngineConfig
