"""PEFT adapter checkpoint loading → stacked LoRA buffers.

HF PEFT layout: adapter_config.json {r, lora_alpha, target_modules} +
adapter_model.safetensors with per-layer tensors
  base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight [r, in]
  base_model.model.model.layers.{i}.self_attn.q_proj.lora_B.weight [out, r]

Output: {target: (A [NL, in, r], B [NL, r, out])} with the alpha/r scaling
folded into B (kubeai_tpu.models.llama LoRA convention). Layers without the
target get zeros.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubeai_tpu.engine.weights import (
    LazyTensors,
    WeightLoadError,
    resolve_model_dir,
)

_HF_TO_NATIVE = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
}


def load_peft_adapter(path_or_url: str, model_cfg, max_rank: int = 16) -> dict:
    adapter_dir = resolve_model_dir(path_or_url)
    cfg_path = os.path.join(adapter_dir, "adapter_config.json")
    if not os.path.exists(cfg_path):
        raise WeightLoadError(f"no adapter_config.json in {adapter_dir}")
    with open(cfg_path) as f:
        acfg = json.load(f)
    r = int(acfg.get("r", 8))
    alpha = float(acfg.get("lora_alpha", r))
    scaling = alpha / r
    if r > max_rank:
        raise WeightLoadError(f"adapter rank {r} exceeds engine max {max_rank}")

    tensors = LazyTensors(adapter_dir)
    NL = model_cfg.num_layers

    out: dict = {}
    for hf_name, native in _HF_TO_NATIVE.items():
        a_list, b_list, found = [], [], False
        for i in range(NL):
            a_key = None
            for pattern in (
                f"base_model.model.model.layers.{i}.self_attn.{hf_name}.lora_A.weight",
                f"model.layers.{i}.self_attn.{hf_name}.lora_A.weight",
            ):
                if pattern in tensors:
                    a_key = pattern
                    break
            if a_key is None:
                a_list.append(None)
                b_list.append(None)
                continue
            found = True
            b_key = a_key.replace("lora_A", "lora_B")
            A = np.asarray(tensors[a_key], np.float32).T  # [in, r]
            B = np.asarray(tensors[b_key], np.float32).T * scaling  # [r, out]
            a_list.append(A)
            b_list.append(B)
        if not found:
            continue
        in_dim = next(a.shape[0] for a in a_list if a is not None)
        out_dim = next(b.shape[1] for b in b_list if b is not None)
        A_stack = np.stack(
            [a if a is not None else np.zeros((in_dim, r), np.float32)
             for a in a_list]
        )
        B_stack = np.stack(
            [b if b is not None else np.zeros((r, out_dim), np.float32)
             for b in b_list]
        )
        out[native] = (A_stack, B_stack)
    if not out:
        raise WeightLoadError(
            f"no supported LoRA targets found in {adapter_dir} "
            f"(supported: {sorted(_HF_TO_NATIVE)})"
        )
    return out
