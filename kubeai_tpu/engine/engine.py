"""Continuous-batching inference engine core.

JetStream-style serving loop, in-process:

  add_request() ──► pending queue
                         │ (free slot?)
                 prefill (bucketed S, jitted) ─► insert KV into slot
                         │
        step(): one batched decode over ALL active slots (jitted, donated
                cache) ─► sample ─► host-side stop checks ─► free slots

TPU-first properties:
  - decode graph compiled ONCE (static [num_slots] batch); prefill compiled
    once per length bucket (powers of two) — bounded recompilation.
  - KV cache buffers are donated through the decode jit: no copy per step.
  - All device work is batched matmuls on the MXU; the host loop only does
    bookkeeping (slot free-lists, stop checks, detokenization upstream).

This engine is what the reference's `engine: VLLM` Pods provide externally
(reference: internal/modelcontroller/engine_vllm.go:12-167); here it is
in-tree and TPU-native. Its admin surface (LoRA load/unload) mirrors
reference: internal/vllmclient/client.go:30-73.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from kubeai_tpu.engine.kvcache import KVCache, insert_sequence
from kubeai_tpu.engine.sampling import SamplingParams, sample
from kubeai_tpu.models.registry import ModelFamily, get_model_family
from kubeai_tpu.parallel import sharding as psh
from kubeai_tpu.parallel.mesh import single_device_mesh
from kubeai_tpu.scheduling.scheduler import (
    CLASS_RANK,
    CLASS_STANDARD,
    RequestScheduler,
)


def _now() -> float:
    """Monotonic clock behind the engine's latency telemetry (queue-wait,
    prefill, TTFT, ITL, e2e). A module-level hook so fake-clock tests can
    monkeypatch ONE symbol and get deterministic timings."""
    return time.monotonic()


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    num_slots: int = 8
    max_seq_len: int = 1024
    # KV cache layout: "paged" (block tables over a shared page pool; decode
    # reads only resident pages — the default) or "slot" (fixed
    # [slots, max_seq_len] reservation per slot). Families without a paged
    # decode path fall back to "slot".
    cache_mode: str = "paged"
    page_size: int = 64
    # Page-pool size. 0 = full reservation (num_slots * max_seq_len worth
    # of pages + the reserved scratch page): identical capacity to the slot
    # cache, no preemption possible. Set smaller to oversubscribe slots —
    # admission defers on pool exhaustion and decode preempts (recompute)
    # the youngest request when it can't grow.
    num_pages: int = 0
    # Batched admission (paged mode): up to this many same-bucket pending
    # prompts prefill in ONE device call — each dispatch costs a full
    # round trip to the chip, so admission under a request burst is
    # dispatch-bound without batching. Rows pad to the next power of two
    # (bounded compile count).
    max_admit_batch: int = 8
    # Speculative decoding (paged mode, families with a verify forward):
    # propose this many tokens per step via prompt-lookup (n-gram match
    # against the request's own context — no draft model) and verify all
    # of them in ONE forward. Accepted tokens cost one model pass total,
    # so repetitive/structured text decodes several tokens per step.
    # Acceptance compares against the same seeded sampler the vanilla
    # path uses, so the stream matches vanilla decoding exactly on the
    # reference backend (CPU tests assert it). On TPU, verify runs its
    # own multi-query Pallas kernel mirroring the decode kernel's
    # per-page online-softmax accumulation — near-tie logits may still
    # differ between the two kernels' schedules, but a speculative
    # engine is internally deterministic. Trade-off: speculation replaces the
    # decode_chunk fused scan with one device call per window — on
    # low-acceptance text that is ~1 token per dispatch instead of
    # decode_chunk, which matters on remote-dispatch transports. 0 = off.
    # Mutually exclusive with pipeline=True.
    speculate: int = 0
    # Adaptive fallback (speculate > 0): speculation trades the fused
    # decode_chunk scan for one device call per window, so on
    # low-acceptance text it emits ~1 token per dispatch where chunk mode
    # emits decode_chunk. Rather than guess the dispatch-latency/compute
    # ratio, the engine MEASURES tokens/second of each mode (EMA over
    # decode calls) and runs the faster one, re-probing the losing mode
    # every spec_probe_every decode calls. Streams are identical in both
    # modes (same seeded sampler), so switching is invisible to clients.
    spec_adaptive: bool = True
    spec_probe_every: int = 32
    prefill_buckets: tuple[int, ...] = ()  # default: powers of 2 up to max
    # Chunked prefill: prompts longer than this are prefilled in fixed
    # [1, prefill_chunk] steps — ONE compiled graph for every prompt
    # length and O(chunk * max_seq_len) activation memory (0 = whole-
    # prompt bucketed prefill only). Works in both cache modes: slot mode
    # chunks straight into the slot's cache row; paged mode stages chunks
    # in a one-slot buffer and scatters pages on the final chunk.
    # Requires family support.
    prefill_chunk: int = 0
    # Automatic prefix caching (paged mode + prefill_chunk > 0): full
    # prompt pages register under a content-hash chain (adapter-aware)
    # when a request completes admission; a later prompt with the same
    # page-aligned prefix ADOPTS those pages read-only and prefills only
    # its suffix — shared system prompts and multi-turn histories skip
    # most prefill compute. Zero-reference pages park in an LRU idle
    # pool and are evicted only when the free list runs dry, so caching
    # never reduces servable capacity. This is the per-replica half of
    # the reference's prefix-caching story (its cross-replica half, the
    # CHWBL router, ships in routing/chwbl.py; reference headline:
    # docs/benchmarks/prefix-aware-load-balancing.md).
    prefix_cache: bool = False
    cache_dtype: Any = jnp.bfloat16
    # KV-cache quantization (paged mode): "" / "bfloat16" stores pages in
    # cache_dtype; "int8" stores pages as int8 with per-token-per-head f32
    # scales riding alongside ({"q8", "scale"} pool leaves — see
    # ops/kv_quant.py), roughly doubling slot capacity at equal HBM
    # (2D/(D+4), 1.94x at D=128) and halving every KV byte shipped by
    # disagg handoff, peer prefix fetch and objstore spill. Quantized
    # pools always use the reference attention path (the Pallas decode
    # kernels are bf16-only) and do not compose with speculation, the
    # fused decode kernel, or pipeline parallelism yet.
    kv_dtype: str = ""
    # Decode steps fused into one device call (lax.scan). Amortizes host
    # dispatch — critical when the chip sits behind an RPC tunnel. Tokens a
    # request emits past its stop point within a chunk are discarded
    # host-side; slot rows are independent, so batch-mates are unaffected.
    decode_chunk: int = 8
    # Weight-only quantization: "" (bf16) or "int8" (per-channel symmetric;
    # halves HBM weight traffic on the memory-bound decode path).
    quantization: str = ""
    # Paged decode attention layout: "" = auto ($KUBEAI_TPU_DECODE_KERNEL,
    # default "per_layer"), "per_layer" = scatter-then-attend inside the
    # layer scan (hardware-validated: 1975.5 tok/s/chip, round 2), "fused"
    # = stacked-pool kernel with deferred scatter (roofline-better, but
    # opt-in until validated on real hardware — its first on-chip dispatch
    # hung).
    decode_kernel: str = ""
    # LoRA hot-swap: number of simultaneously loaded adapters (0 disables
    # the LoRA path entirely — no extra compute in the compiled graphs).
    max_adapters: int = 0
    max_lora_rank: int = 16
    # Pipelined stepping: dispatch decode chunk N+1 before fetching chunk
    # N's tokens, so the device computes through the host's fetch+process
    # time. Costs one chunk of extra stop-check latency. Default OFF: some
    # remote-dispatch transports (e.g. relayed single-chip tunnels) stall
    # with a second donated-buffer program in flight behind a pending
    # fetch; direct PJRT targets can enable it safely.
    pipeline: bool = False
    # Overlapped step pipeline: "auto" (default — overlap ON wherever the
    # topology allows it), "on" (require overlap; typed
    # StepOverlapUnsupported where it can't run), "off" (synchronous
    # loop). When on, step() dispatches decode chunk N+1 BEFORE reaping
    # chunk N's tokens, so readback, scheduler admission, detokenize and
    # SSE fan-out for chunk N run concurrently with chunk N+1's device
    # compute. Conservative barriers (pending admissions, cancel/release,
    # drain, handoff export/import, prefix-page export/import, and any
    # speculation window) force a reap before state mutates, so greedy
    # AND seeded streams are token-identical to the synchronous loop.
    # Auto-off for pipeline parallelism (pp > 1) and lockstep multihost.
    # Subsumes the legacy `pipeline` bool (pipeline=True == "on").
    step_overlap: str = "auto"
    # Pipeline parallelism (mesh pp axis > 1): decode microbatch count for
    # the GPipe schedule. 0 = the pp stage count (steady-state utilization
    # M/(M+P-1); raise toward num_slots for higher utilization at smaller
    # per-tick batches). Requires a family with decode_step_paged_pp,
    # paged cache mode, and num_slots % M == 0; composes with dp, tp, sp
    # (ring-attention prefill), int8 quantization, and prompt-lookup
    # speculation.
    pp_microbatches: int = 0

    def buckets(self) -> tuple[int, ...]:
        if self.prefill_buckets:
            return self.prefill_buckets
        b, out = 16, []
        while b < self.max_seq_len:
            out.append(b)
            b *= 2
        out.append(self.max_seq_len)
        return tuple(out)

    def effective_num_pages(self) -> int:
        if self.num_pages > 0:
            return self.num_pages
        per_slot = -(-self.max_seq_len // self.page_size)
        return 1 + self.num_slots * per_slot  # +1: reserved scratch page 0


class StepOverlapUnsupported(ValueError):
    """step_overlap='on' requested in a topology that cannot overlap
    (pipeline parallelism, lockstep multihost): a second in-flight
    program would race the GPipe stage handoffs / desynchronize the
    per-step cross-host broadcast. 'auto' degrades to the synchronous
    loop instead of raising."""


class StepEvent(NamedTuple):
    """One emitted token. `finish_reason` is "" while the request is live,
    else "stop" | "length" | "cancelled" (OpenAI finish_reason semantics)."""

    rid: int
    token: int
    finished: bool
    finish_reason: str = ""


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: list[int]
    params: SamplingParams
    seed: int
    adapter_idx: int = 0  # 0 = no adapter
    # Scheduling identity: the priority class the scheduler resolved for
    # this request (preemption prefers evicting the lowest class) and the
    # fairness key it was queued under.
    priority: str = CLASS_STANDARD
    client: str = ""
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    position: int = 0  # absolute position of the next token to decode
    last_token: int = 0
    done: bool = False
    finish_reason: str = ""  # "stop" | "length" (OpenAI semantics)
    stop_token_ids: tuple[int, ...] = ()
    # Incremental context buffer + n-gram last-occurrence index for
    # speculative prompt-lookup (built on first use; appended per emitted
    # token — proposal lookup is O(γ) per step, never an O(L) rescan).
    ctx: Any = None
    ctx_len: int = 0
    ngram_idx: Any = None  # {n: {ngram tuple -> last start index}}
    ngram_upto: Any = None  # {n: window starts indexed so far}
    # Lifecycle timestamps (_now() clock) for the latency telemetry the
    # serve loop drains into histograms. t_enqueue doubles as the "e2e not
    # yet recorded" flag (zeroed after recording); t_admit_start survives
    # preemption so a resumed request keeps its ORIGINAL queue-wait.
    t_enqueue: float = 0.0
    t_admit_start: float = 0.0
    t_prev_token: float = 0.0


class EngineDraining(RuntimeError):
    """Raised by add_request once drain has begun: the server answers
    503 + Retry-After so the LB moves the request to another replica."""


class EngineBusy(RuntimeError):
    """Raised by the synchronous disaggregation paths (export_handoff /
    import_handoff) when no slot or KV pages are free RIGHT NOW: unlike
    add_request there is no queue to park in, so the server sheds with
    429 and the router re-picks a less-loaded replica."""


class Engine:
    """Single-model, single-mesh continuous-batching engine."""

    def __init__(
        self,
        family: ModelFamily | str,
        model_cfg: Any,
        params: Any,
        mesh: Mesh | None = None,
        cfg: EngineConfig = EngineConfig(),
        rules: psh.ShardingRules = psh.DEFAULT_RULES,
        eos_token_ids: tuple[int, ...] = (),
        draft: tuple[Any, Any] | None = None,
        scheduler: RequestScheduler | None = None,
    ):
        """`draft`: optional (draft_cfg, draft_params) — a small same-family
        model that PROPOSES the speculative window (cfg.speculate > 0)
        instead of prompt-lookup. Prompt-lookup's acceptance collapses on
        non-repetitive text; a draft model proposes from actual model
        probabilities, so acceptance tracks draft/target agreement. The
        draft keeps its own slot KV cache: each window feeds it the true
        last emitted token at its true position, so accepted proposals'
        KV (written during proposal) is correct and rejected positions
        are masked (length = position+1) until overwritten. Verify
        guarantees the emitted stream is exact regardless of proposal
        quality."""
        self.family = (
            get_model_family(family) if isinstance(family, str) else family
        )
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else single_device_mesh()
        self.rules = rules
        self.eos_token_ids = eos_token_ids
        self._lock = threading.Lock()
        self._next_rid = 0
        # Graceful drain: once set, add_request refuses (EngineDraining)
        # while in-flight generations run to completion — the server's
        # drain sequence flips this before it stops the HTTP front so
        # the admission race window is closed at the source.
        self._draining = False
        # SLO-aware pending queue: priority bands with strict precedence,
        # WFQ within a band keyed by client, deadline-aware admission
        # (kubeai_tpu/scheduling). Replaces the former FIFO deque.
        self._sched = scheduler if scheduler is not None else RequestScheduler()
        self._active: dict[int, _Request] = {}  # slot -> request
        self._requests: dict[int, _Request] = {}
        self._free_slots = list(range(cfg.num_slots))
        # In-flight decode chunk (overlapped stepping): (token futures,
        # snapshot of the slot->request map the chunk was dispatched
        # with, chunk length in model steps, monotonic dispatch time).
        # The dispatch timestamp feeds the server watchdog: a dispatched
        # chunk counts as progress until its own reap deadline ages out.
        self._inflight: tuple | None = None
        # Base entropy for unseeded requests (per-request seed = base ^ rid).
        self._seed_base = int.from_bytes(np.random.bytes(4), "little")
        self._steps = 0
        # Adaptive speculation: measured tokens/s EMA per decode mode
        # ("spec" | "chunk"); None until a mode's SECOND call (the first
        # includes compile and would poison the estimate).
        self._mode_tps: dict[str, float | None] = {}
        self._mode_calls: dict[str, int] = {}
        self._decode_calls = 0
        # Speculation acceptance: proposed/accepted counts over live
        # slots (windows = spec steps × live slots). Reading it after a
        # run answers "did the proposer earn its keep" — the draft's
        # whole point vs prompt-lookup on non-repetitive text.
        self.spec_stats = {"windows": 0, "proposed": 0, "accepted": 0}
        # Request-lifecycle latency observations, (kind, seconds) with
        # kind ∈ {queue_wait, prefill, ttft, itl, e2e}. The serve loop
        # drains these into histograms (drain_timing) — the engine core
        # never touches a metrics registry, so the hot loop stays free of
        # registry locks.
        self._timing: list[tuple[str, float]] = []
        # Snapshot of the most recent step() for per-decode-step gauges:
        # running batch size, waiting-queue depth, tokens emitted, wall
        # duration.
        self.last_step_stats: dict[str, float] = {}
        # Per-phase step profiler (kubeai_tpu/fleet/profiler): step()
        # fills `_phase_scratch` with monotonic phase durations and
        # closes each step into the profiler's ring; the serve loop
        # drains it into the kubeai_engine_step_phase_seconds histogram
        # and POST /v1/profile reads the ring. Plain float bookkeeping
        # under the engine lock — no registry in the hot path.
        from kubeai_tpu.fleet.profiler import StepProfiler

        self.profiler = StepProfiler()
        self._phase_scratch: dict[str, float] | None = None

        # Resolve the cache mode: paged needs family support; otherwise
        # fall back to the slot cache. Chunked prefill works in both modes
        # (paged stages chunks in a one-slot buffer, then scatters).
        self.cache_mode = cfg.cache_mode
        self._spec = 0  # resolved speculation window (see below)
        if cfg.cache_mode == "paged" and (
            getattr(self.family, "decode_step_paged", None) is None
        ):
            self.cache_mode = "slot"
        elif cfg.cache_mode not in ("paged", "slot"):
            raise ValueError(f"unknown cache_mode {cfg.cache_mode!r}")

        # Paged decode attention layout ("" = $KUBEAI_TPU_DECODE_KERNEL,
        # default per_layer — the hardware-validated path; "fused" is the
        # deferred-scatter kernel, opt-in until a real-TPU A/B clears it).
        from kubeai_tpu.ops.paged_attention import resolve_decode_kernel

        self.decode_kernel = resolve_decode_kernel(cfg.decode_kernel)

        # KV quantization: validated here, materialized in the paged
        # branch below ({"q8", "scale"} pool leaves; ops/kv_quant.py).
        from kubeai_tpu.ops.kv_quant import resolve_kv_dtype

        self.kv_dtype = resolve_kv_dtype(cfg.kv_dtype)
        self._kv_quant = self.kv_dtype == "int8"
        if self._kv_quant:
            if self.cache_mode != "paged":
                raise ValueError(
                    "kv_dtype='int8' requires cache_mode='paged' (pages "
                    "are the quantization unit)"
                )
            if cfg.speculate > 0 or draft is not None:
                raise ValueError(
                    "kv_dtype='int8' does not compose with speculative "
                    "decoding yet (the verify kernels read bf16 pools)"
                )
            if self.decode_kernel == "fused":
                raise ValueError(
                    "kv_dtype='int8' does not compose with "
                    "decode_kernel='fused' (the fused kernel reads a "
                    "stacked bf16 pool); use per_layer"
                )

        # Pipeline parallelism: stage-local layers + KV over the pp mesh
        # axis (GPipe microbatched decode; see models/llama.py
        # decode_step_paged_pp). Composes with dp AND tp — the pp
        # shard_map is manual over pp only (axis_names), so Megatron tp
        # sharding stays GSPMD-managed inside each stage (the 70B/v5e-8
        # plan is pp=2 × tp=4). Composes with sp too (ring-attention
        # prefill; see below). Scope: paged cache, llama-family.
        self._pp = self.mesh.shape.get("pp", 1)
        self._pp_microbatches = 0
        if self._pp > 1:
            if self._kv_quant:
                raise ValueError(
                    "kv_dtype='int8' does not compose with pipeline "
                    "parallelism yet (the pp shard_map moves raw bf16 "
                    "pools)"
                )
            if getattr(self.family, "decode_step_paged_pp", None) is None:
                raise ValueError(
                    f"family {self.family.name} does not support pipeline "
                    "parallelism (no decode_step_paged_pp)"
                )
            if self.cache_mode != "paged":
                raise ValueError("pipeline parallelism requires cache_mode='paged'")
            # sp composes: prefill runs ring attention over the sp axis
            # (resolve_prefill binds the mesh) while the pp decode
            # shard_map simply replicates its per-tick microbatch inputs
            # over sp — decode is single-token, so the sequence axis has
            # nothing to shard there.
            if model_cfg.num_layers % self._pp:
                raise ValueError(
                    f"{model_cfg.num_layers} layers not divisible by "
                    f"pp={self._pp} stages"
                )
            m = cfg.pp_microbatches or self._pp
            if cfg.num_slots % m:
                raise ValueError(
                    f"num_slots={cfg.num_slots} not divisible by "
                    f"pp_microbatches={m}"
                )
            self._pp_microbatches = m

        # Overlapped stepping: resolve the tri-state knob against the
        # topology. pp > 1 already fills the device with microbatch ticks
        # inside ONE call and a second in-flight donated-buffer program
        # would race the stage handoffs, so explicit "on" is a typed
        # refusal and "auto" stays synchronous. (Lockstep multihost is
        # enforced one level up — LockstepEngine / server main — because
        # the engine cannot see its wrapper.)
        overlap = cfg.step_overlap
        if isinstance(overlap, bool):
            overlap = "on" if overlap else "off"
        overlap = (overlap or "auto").strip().lower()
        if overlap not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown step_overlap {cfg.step_overlap!r} "
                "(expected 'auto' | 'on' | 'off')"
            )
        if overlap == "auto" and cfg.pipeline:
            overlap = "on"  # legacy knob: pipeline=True meant depth-1 overlap
        if self._pp > 1:
            if overlap == "on":
                raise StepOverlapUnsupported(
                    "step_overlap='on' does not compose with pipeline "
                    "parallelism (pp>1): the GPipe decode schedule already "
                    "keeps the device busy with microbatch ticks and a "
                    "second in-flight program would race the stage "
                    "handoffs; use step_overlap='auto' or 'off'"
                )
            overlap = "off"
        # Resolved: the step loop overlaps unless something said no.
        self._overlap = overlap != "off"
        # Events reaped OUTSIDE step() (barrier reaps in cancel/drain/
        # handoff/prefix paths): queued here, prepended to the next
        # step()'s return so no token is ever dropped.
        self._pending_events: list[StepEvent] = []

        # Quantize (optional), then shard params onto the mesh.
        specs = self.family.param_specs(model_cfg)
        if cfg.quantization == "int8":
            from kubeai_tpu.engine.quantization import (
                quantize_params,
                quantized_specs,
            )

            params = quantize_params(params)
            specs = quantized_specs(specs, params["layers"])
        elif cfg.quantization:
            raise ValueError(f"unknown quantization {cfg.quantization!r}")
        self.params = psh.shard_params(params, specs, self.mesh, rules)

        # GQA: when tp exceeds the KV-head count the cache can't shard on
        # heads — replicate it across tp (each shard attends with its local
        # q heads against the full KV; standard GQA-on-TPU fallback).
        cache_rules = rules
        tp_size = self.mesh.shape.get("tp", 1)
        if model_cfg.num_kv_heads % max(tp_size, 1) != 0:
            cache_rules = psh.ShardingRules(
                rules=tuple(
                    (name, None if name == psh.KV_HEADS else phys)
                    for name, phys in rules.rules
                )
            )

        self.prefix_stats = {"lookups": 0, "hit_tokens": 0, "prompt_tokens": 0}
        # Disaggregation accounting (cumulative; the server converts
        # these to counters): handoffs exported after prefill, handoffs
        # imported into decode slots, KV bytes in each direction.
        self.disagg_stats = {
            "exported": 0,
            "imported": 0,
            "exported_bytes": 0,
            "imported_bytes": 0,
        }
        # Cluster KV-sharing accounting (cumulative, server folds into
        # counters): partial-chain pages served to peers / seeded from
        # peers, and objstore spill/fill traffic.
        self.kv_share_stats = {
            "exported_pages": 0,
            "exported_bytes": 0,
            "imported_pages": 0,
            "imported_bytes": 0,
            "spilled_pages": 0,
            "filled_pages": 0,
        }
        if self.cache_mode == "paged":
            from kubeai_tpu.engine.paged_cache import PageAllocator, PagedKVCache

            n_pages = cfg.effective_num_pages()
            self._n_pages = n_pages
            max_pages = -(-cfg.max_seq_len // cfg.page_size)
            # Pages replicated across dp (page ids are global); KV heads on
            # tp exactly like the slot cache; the layer axis shards over
            # pp so each pipeline stage holds only its own layers' pages.
            pool_sharding = psh.named_sharding(
                self.mesh,
                (psh.LAYERS, None, None, psh.KV_HEADS, None),
                cache_rules,
            )
            if self._kv_quant:
                # Dict pool leaves: int8 pages shard like bf16 pages; the
                # [NL, pages, page, KVH] scale leaf drops the head_dim
                # axis. device_put and jit out_shardings both take the
                # pytree form.
                pool_sharding = {
                    "q8": pool_sharding,
                    "scale": psh.named_sharding(
                        self.mesh,
                        (psh.LAYERS, None, None, psh.KV_HEADS),
                        cache_rules,
                    ),
                }
            if n_pages - 1 < max_pages:
                raise ValueError(
                    f"num_pages={n_pages} cannot hold one max_seq_len "
                    f"sequence ({max_pages} pages + scratch); preemption "
                    "could not guarantee progress"
                )
            self.cache = PagedKVCache.create(
                model_cfg.num_layers,
                n_pages,
                cfg.page_size,
                cfg.num_slots,
                cfg.max_seq_len,
                model_cfg.num_kv_heads,
                model_cfg.head_size,
                dtype="int8" if self._kv_quant else cfg.cache_dtype,
            )
            self.cache.k_pages = jax.device_put(self.cache.k_pages, pool_sharding)
            self.cache.v_pages = jax.device_put(self.cache.v_pages, pool_sharding)
            self._bt_sharding = psh.named_sharding(
                self.mesh, (None, None), cache_rules
            )
            self.cache.block_tables = jax.device_put(
                self.cache.block_tables, self._bt_sharding
            )
            self._alloc = PageAllocator(
                n_pages, cfg.page_size, max_pages_per_slot=max_pages
            )
            self._prefix_cache = bool(cfg.prefix_cache)
            if self._prefix_cache:
                if cfg.prefill_chunk <= 0:
                    raise ValueError(
                        "prefix_cache needs prefill_chunk > 0 (cache hits "
                        "prefill only the uncached suffix, which runs "
                        "through the staged-chunk path)"
                    )
                if self._pp > 1:
                    raise ValueError(
                        "prefix_cache does not compose with pipeline "
                        "parallelism yet"
                    )
                if (cfg.max_seq_len - cfg.prefill_chunk) // cfg.page_size < 1:
                    # The adoptable prefix is capped at max_seq_len -
                    # prefill_chunk (the padded suffix chunk must fit the
                    # staging buffer); at or past the cap the cache can
                    # NEVER hit and every admission pays pure hashing
                    # overhead.
                    import logging

                    logging.getLogger(__name__).warning(
                        "prefix_cache is inert: prefill_chunk=%d leaves "
                        "no adoptable pages under max_seq_len=%d "
                        "(page_size=%d) — shrink prefill_chunk",
                        cfg.prefill_chunk, cfg.max_seq_len, cfg.page_size,
                    )
            # Host mirror of the block tables: page growth/release edits
            # this; one small [slots, MP] transfer syncs the device copy
            # before the next decode dispatch (_bt_dirty).
            self._bt_host = np.full((cfg.num_slots, max_pages), -1, np.int32)
            self._bt_dirty = False
            cache_sharding = pool_sharding
            # Chunked prefill staging: chunks write a ONE-slot [NL, L,
            # KVH, D] buffer (the exact layout the chunk graph already
            # speaks); the last chunk scatters the staged sequence through
            # the block tables in the same device call. Costs one slot's
            # KV of extra HBM, keeps the single compiled chunk graph.
            self._stage_k = self._stage_v = None
            if cfg.prefill_chunk > 0:
                self._stage_sharding = psh.named_sharding(
                    self.mesh, (None, None, psh.KV_HEADS, None), cache_rules
                )
                stage_shape = (
                    model_cfg.num_layers,
                    cfg.max_seq_len,
                    model_cfg.num_kv_heads,
                    model_cfg.head_size,
                )
                self._stage_k = jax.device_put(
                    jnp.zeros(stage_shape, cfg.cache_dtype),
                    self._stage_sharding,
                )
                self._stage_v = jax.device_put(
                    jnp.zeros(stage_shape, cfg.cache_dtype),
                    self._stage_sharding,
                )
        else:
            if cfg.prefix_cache:
                raise ValueError(
                    "prefix_cache requires cache_mode='paged' (pages are "
                    "the sharing unit)"
                )
            self._prefix_cache = False
            cache_sharding = psh.named_sharding(
                self.mesh, KVCache.logical_axes(), cache_rules
            )
            self.cache = KVCache.create(
                model_cfg.num_layers,
                cfg.num_slots,
                cfg.max_seq_len,
                model_cfg.num_kv_heads,
                model_cfg.head_size,
                dtype=cfg.cache_dtype,
                sharding=cache_sharding,
            )

        # Per-slot decode state lives ON DEVICE (replicated): steady-state
        # decode then needs ZERO host->device transfers per chunk — critical
        # when each transfer costs a network round trip to the chip.
        B = cfg.num_slots
        self._state = {
            "tokens": jnp.zeros((B,), jnp.int32),
            "positions": jnp.zeros((B,), jnp.int32),
            "seeds": jnp.zeros((B,), jnp.uint32),
            "temp": jnp.zeros((B,), jnp.float32),
            "topk": jnp.zeros((B,), jnp.int32),
            "topp": jnp.ones((B,), jnp.float32),
            "lora_idx": jnp.zeros((B,), jnp.int32),
        }

        # LoRA adapter buffers: fixed shapes, slot 0 = zeros ("no adapter").
        # Loading an adapter updates a buffer slice — never a recompile.
        self._lora = None
        self._adapter_slots: dict[str, int] = {}
        # slot index -> weight generation (prefix-cache hash seed; index
        # 0 = base model, generation fixed at 0).
        self._adapter_gen: dict[int, int] = {}
        if cfg.max_adapters > 0:
            if not hasattr(self.family, "init_lora_buffers"):
                from kubeai_tpu.models import llama as _llama

                init_fn = _llama.init_lora_buffers
            else:
                init_fn = self.family.init_lora_buffers
            self._lora = init_fn(
                model_cfg, cfg.max_adapters + 1, cfg.max_lora_rank
            )
            self._adapter_free = list(range(1, cfg.max_adapters + 1))

        # Chunked-prefill support is resolved ONCE here; both cache-mode
        # builders reuse it.
        self._chunk_fn = None
        if cfg.prefill_chunk > 0:
            self._chunk_fn = getattr(self.family, "prefill_chunk", None)
            if self._chunk_fn is None:
                raise ValueError(
                    f"family {self.family.name} does not support chunked prefill"
                )

        self._draft = None
        if cfg.speculate > 0:
            if cfg.pipeline:
                raise ValueError("speculate and pipeline are mutually exclusive")
            if (
                self.cache_mode == "paged"
                and getattr(self.family, "decode_verify_paged", None)
                is not None
                and (
                    self._pp == 1
                    or getattr(self.family, "decode_verify_paged_pp", None)
                    is not None
                )
            ):
                self._spec = cfg.speculate
                if draft is not None:
                    if self._pp > 1:
                        # The draft runs the non-pp decode path; its
                        # layer stack would shard over pp and every
                        # draft step would all-gather it. Prompt-lookup
                        # speculation is the pp-compatible mode.
                        raise ValueError(
                            "draft-model speculation does not compose "
                            "with pipeline parallelism (use prompt-"
                            "lookup speculation: speculate>0, no draft)"
                        )
                    dcfg, dparams = draft
                    self._draft_cfg = dcfg
                    # Small drafts often have fewer KV heads than tp: fall
                    # back to replicated KV heads for BOTH the draft's
                    # params and its cache (the same GQA-on-TPU fallback
                    # the main cache uses).
                    dc_rules = rules
                    if dcfg.num_kv_heads % max(
                        self.mesh.shape.get("tp", 1), 1
                    ):
                        dc_rules = psh.ShardingRules(
                            rules=tuple(
                                (n, None if n == psh.KV_HEADS else p)
                                for n, p in rules.rules
                            )
                        )
                    self._draft_params = psh.shard_params(
                        dparams, self.family.param_specs(dcfg), self.mesh,
                        dc_rules,
                    )
                    self._draft_sharding = psh.named_sharding(
                        self.mesh, KVCache.logical_axes(), dc_rules
                    )
                    dc = KVCache.create(
                        dcfg.num_layers, cfg.num_slots, cfg.max_seq_len,
                        dcfg.num_kv_heads, dcfg.head_size, cfg.cache_dtype,
                        sharding=self._draft_sharding,
                    )
                    self._dk, self._dv = dc.k, dc.v
                    self._draft = True
            else:
                if draft is not None:
                    # A draft is explicit caller intent (weights were
                    # loaded for it) — dropping it silently would hide
                    # the misconfiguration.
                    raise ValueError(
                        "draft model provided but speculation is "
                        f"unavailable (cache_mode={self.cache_mode!r}, "
                        f"pp={self._pp}, family verify="
                        f"{getattr(self.family, 'decode_verify_paged', None) is not None})"
                    )
                import logging

                logging.getLogger(__name__).warning(
                    "speculate=%d requested but unavailable (cache_mode=%s, "
                    "family verify=%s) — running vanilla decode",
                    cfg.speculate, self.cache_mode,
                    getattr(self.family, "decode_verify_paged", None)
                    is not None,
                )
        elif draft is not None:
            raise ValueError(
                "draft model provided but cfg.speculate == 0"
            )

        self._build_jits(cache_sharding)

    # ---- compiled functions -------------------------------------------------

    def _resolve_prefill(self):
        """Family prefill, with the engine mesh bound when an sp axis is
        live and the family supports ring-attention prefill (llama/qwen):
        makes sequence parallelism a serving path, not a demo."""
        import inspect
        from functools import partial as _partial

        fam = self.family
        if (
            self.mesh.shape.get("sp", 1) > 1
            and "mesh" in inspect.signature(fam.prefill).parameters
        ):
            return _partial(fam.prefill, mesh=self.mesh)
        return fam.prefill

    def _build_jits(self, cache_sharding) -> None:
        if self.cache_mode == "paged":
            self._build_jits_paged(cache_sharding)
            return
        fam, mcfg = self.family, self.model_cfg
        prefill_fn = self._resolve_prefill()
        max_len = self.cfg.max_seq_len
        chunk = max(1, self.cfg.decode_chunk)

        def _prefill_admit(params, tokens, ints, floats, ck, cv, state, lora):
            """Fused prefill → cache insert → first-token sample → slot-state
            update: ONE device call per admitted request. `ints` packs
            [length, slot, seed, top_k, adapter, forced]; `floats` packs
            [temp, top_p] — two small transfers instead of seven.
            forced >= 0 overrides the sampled token (preemption / stream
            resume — cross-graph re-sampling could diverge by ULPs)."""
            length, slot, seed, topk = ints[0], ints[1], ints[2], ints[3]
            adapter, forced = ints[4], ints[5]
            temp, topp = floats[0], floats[1]
            if lora is None:
                logits, k_all, v_all = prefill_fn(
                    params, mcfg, tokens, length[None]
                )
            else:
                logits, k_all, v_all = prefill_fn(
                    params, mcfg, tokens, length[None],
                    lora=lora, lora_idx=adapter[None],
                )
            ck, cv = insert_sequence(ck, cv, k_all[:, 0], v_all[:, 0], slot)
            tok = sample(
                logits,
                seed.astype(jnp.uint32)[None],
                length[None],
                temp[None],
                topk[None],
                topp[None],
            )[0]
            tok = jnp.where(forced >= 0, forced, tok)
            state = dict(
                tokens=state["tokens"].at[slot].set(tok),
                positions=state["positions"].at[slot].set(length),
                seeds=state["seeds"].at[slot].set(seed.astype(jnp.uint32)),
                temp=state["temp"].at[slot].set(temp),
                topk=state["topk"].at[slot].set(topk),
                topp=state["topp"].at[slot].set(topp),
                lora_idx=state["lora_idx"].at[slot].set(adapter),
            )
            return tok, ck, cv, state

        self._prefill_admit_jit = jax.jit(
            _prefill_admit,
            donate_argnums=(4, 5, 6),
            out_shardings=(None, cache_sharding, cache_sharding, None),
            static_argnames=(),
        )

        def _decode_chunk(params, ck, cv, state, lora):
            """`chunk` decode steps fused via lax.scan; emits [chunk, B]
            tokens per device call. No host inputs besides the (donated,
            device-resident) cache and slot state. Write positions are
            clamped so rows that pass their stop point within a chunk stay
            in-bounds (their surplus tokens are discarded host-side)."""
            seeds, temp = state["seeds"], state["temp"]
            topk, topp = state["topk"], state["topp"]

            def body(carry, _):
                tokens, positions, ck, cv = carry
                if lora is None:
                    logits, ck, cv = fam.decode_step(
                        params, mcfg, tokens, positions, ck, cv
                    )
                else:
                    logits, ck, cv = fam.decode_step(
                        params, mcfg, tokens, positions, ck, cv,
                        lora=lora, lora_idx=state["lora_idx"],
                    )
                # Sampled token lands at position+1 — the fold-in value, so
                # a seeded request replays identically across batches.
                toks = sample(logits, seeds, positions + 1, temp, topk, topp)
                next_pos = jnp.minimum(positions + 1, max_len - 1)
                return (toks, next_pos, ck, cv), toks

            (tokens, positions, ck, cv), toks_seq = jax.lax.scan(
                body,
                (state["tokens"], state["positions"], ck, cv),
                None,
                length=chunk,
            )
            state = dict(state, tokens=tokens, positions=positions)
            return toks_seq, ck, cv, state

        self._decode_jit = jax.jit(
            _decode_chunk,
            donate_argnums=(1, 2, 3),
            out_shardings=(None, cache_sharding, cache_sharding, None),
        )

        if self.cfg.prefill_chunk > 0:
            chunk_fn = self._chunk_fn

            def _slot_slice(c, slot):
                nl, _, L, kvh, d = c.shape
                sl = jax.lax.dynamic_slice(
                    c, (0, slot, 0, 0, 0), (nl, 1, L, kvh, d)
                )
                return sl[:, 0]

            def _slot_write(c, slot, sl):
                return jax.lax.dynamic_update_slice(
                    c, sl[:, None].astype(c.dtype), (0, slot, 0, 0, 0)
                )

            def _chunk_mid(params, tokens, ints, ck, cv, lora):
                start, slot, length, adapter = ints[0], ints[1], ints[2], ints[3]
                ks, vs = _slot_slice(ck, slot), _slot_slice(cv, slot)
                _, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=False,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                return _slot_write(ck, slot, ks), _slot_write(cv, slot, vs)

            self._prefill_chunk_mid_jit = jax.jit(
                _chunk_mid,
                donate_argnums=(3, 4),
                static_argnums=(),
                out_shardings=(cache_sharding, cache_sharding),
            )

            def _chunk_last(params, tokens, ints, floats, ck, cv, state, lora):
                start, slot, length = ints[0], ints[1], ints[2]
                adapter, seed, topk = ints[3], ints[4], ints[5]
                forced = ints[6]
                temp, topp = floats[0], floats[1]
                ks, vs = _slot_slice(ck, slot), _slot_slice(cv, slot)
                logits, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=True,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                ck = _slot_write(ck, slot, ks)
                cv = _slot_write(cv, slot, vs)
                tok = sample(
                    logits,
                    seed.astype(jnp.uint32)[None],
                    length[None],
                    temp[None],
                    topk[None],
                    topp[None],
                )[0]
                tok = jnp.where(forced >= 0, forced, tok)
                state = dict(
                    tokens=state["tokens"].at[slot].set(tok),
                    positions=state["positions"].at[slot].set(length),
                    seeds=state["seeds"].at[slot].set(seed.astype(jnp.uint32)),
                    temp=state["temp"].at[slot].set(temp),
                    topk=state["topk"].at[slot].set(topk),
                    topp=state["topp"].at[slot].set(topp),
                    lora_idx=state["lora_idx"].at[slot].set(adapter),
                )
                return tok, ck, cv, state

            self._prefill_chunk_last_jit = jax.jit(
                _chunk_last,
                donate_argnums=(4, 5, 6),
                out_shardings=(None, cache_sharding, cache_sharding, None),
            )

    def _build_jits_paged(self, pool_sharding) -> None:
        """Paged-cache compiled paths: admission scatters the prefilled
        sequence through the slot's block-table row; decode scatters one
        token per slot and attends over resident pages only."""
        fam, mcfg = self.family, self.model_cfg
        prefill_fn = self._resolve_prefill()
        max_len = self.cfg.max_seq_len
        chunk = max(1, self.cfg.decode_chunk)
        page = self.cfg.page_size
        if self._pp > 1:
            from functools import partial as _partial

            decode_paged = _partial(
                fam.decode_step_paged_pp,
                mesh=self.mesh,
                microbatches=self._pp_microbatches,
            )
        else:
            from functools import partial as _partial

            decode_paged = _partial(
                fam.decode_step_paged, attn_kernel=self.decode_kernel
            )

        def _prefill_admit(
            params, tokens, ints, floats, bt_rows, kp, vp, bt, state, lora
        ):
            """BATCHED admission: prefill [A, S] prompts → page scatter →
            first-token sample → state update, ONE device call for up to
            max_admit_batch same-bucket prompts (each dispatch is a chip
            round trip — admission under bursts is dispatch-bound).

            ints [A, 6] packs per row [length, slot, seed, top_k,
            adapter, forced]; floats [A, 2] packs [temp, top_p];
            bt_rows [A, MP] are the freshly allocated block-table rows.
            forced >= 0 overrides the sampled token (preemption resume —
            re-sampling could diverge across kernels). PADDING rows use
            slot = num_slots: their scatter indices are out of bounds and
            jit scatters DROP OOB writes, so they touch nothing (their
            page writes go to scratch page 0 via bt_row = -1)."""
            lengths = ints[:, 0]
            slots = ints[:, 1]
            seeds = ints[:, 2].astype(jnp.uint32)
            topk = ints[:, 3]
            adapters = ints[:, 4]
            forced = ints[:, 5]
            temp, topp = floats[:, 0], floats[:, 1]
            if lora is None:
                logits, k_all, v_all = prefill_fn(params, mcfg, tokens, lengths)
            else:
                logits, k_all, v_all = prefill_fn(
                    params, mcfg, tokens, lengths,
                    lora=lora, lora_idx=adapters,
                )
            # Per-row page coordinates: [A, S] ids/offsets; padded tails
            # (and padding rows) land in reserved scratch page 0.
            from kubeai_tpu.ops.paged_attention import (
                batched_scatter_sequence,
                batched_sequence_page_coords,
            )

            page_ids, offsets = batched_sequence_page_coords(
                bt_rows, lengths, tokens.shape[1], page
            )
            kp, vp = batched_scatter_sequence(
                kp, vp, k_all, v_all, page_ids, offsets
            )
            bt = bt.at[slots].set(bt_rows)
            toks = sample(logits, seeds, lengths, temp, topk, topp)  # [A]
            toks = jnp.where(forced >= 0, forced, toks)
            state = dict(
                tokens=state["tokens"].at[slots].set(toks),
                positions=state["positions"].at[slots].set(lengths),
                seeds=state["seeds"].at[slots].set(seeds),
                temp=state["temp"].at[slots].set(temp),
                topk=state["topk"].at[slots].set(topk),
                topp=state["topp"].at[slots].set(topp),
                lora_idx=state["lora_idx"].at[slots].set(adapters),
            )
            return toks, kp, vp, bt, state

        self._prefill_admit_jit = jax.jit(
            _prefill_admit,
            donate_argnums=(5, 6),
            out_shardings=(
                None, pool_sharding, pool_sharding, self._bt_sharding, None,
            ),
        )

        def _decode_chunk(params, kp, vp, bt, state, lora):
            """`chunk` paged decode steps fused via lax.scan. The block
            tables are read-only here — page growth happens host-side
            between chunks (the host ensures pages cover position+chunk
            before dispatching)."""
            seeds, temp = state["seeds"], state["temp"]
            topk, topp = state["topk"], state["topp"]

            def body(carry, _):
                tokens, positions, kp, vp = carry
                if lora is None:
                    logits, kp, vp = decode_paged(
                        params, mcfg, tokens, positions, kp, vp, bt
                    )
                else:
                    logits, kp, vp = decode_paged(
                        params, mcfg, tokens, positions, kp, vp, bt,
                        lora=lora, lora_idx=state["lora_idx"],
                    )
                toks = sample(logits, seeds, positions + 1, temp, topk, topp)
                next_pos = jnp.minimum(positions + 1, max_len - 1)
                return (toks, next_pos, kp, vp), toks

            (tokens, positions, kp, vp), toks_seq = jax.lax.scan(
                body,
                (state["tokens"], state["positions"], kp, vp),
                None,
                length=chunk,
            )
            state = dict(state, tokens=tokens, positions=positions)
            return toks_seq, kp, vp, state

        self._decode_jit = jax.jit(
            _decode_chunk,
            donate_argnums=(1, 2),
            out_shardings=(None, pool_sharding, pool_sharding, None),
        )

        from kubeai_tpu.ops.paged_attention import (
            scatter_sequence as _scatter_seq,
            sequence_page_coords as _seq_coords,
        )

        def _slot_resume_state(state, ints, floats):
            """Shared handoff-import state update. `ints` packs [length,
            slot, seed, top_k, adapter, first_token]; `floats` packs
            [temp, top_p]."""
            length, slot = ints[0], ints[1]
            seed = ints[2].astype(jnp.uint32)
            topk, adapter, first = ints[3], ints[4], ints[5]
            temp, topp = floats[0], floats[1]
            return dict(
                tokens=state["tokens"].at[slot].set(first),
                positions=state["positions"].at[slot].set(length),
                seeds=state["seeds"].at[slot].set(seed),
                temp=state["temp"].at[slot].set(temp),
                topk=state["topk"].at[slot].set(topk),
                topp=state["topp"].at[slot].set(topp),
                lora_idx=state["lora_idx"].at[slot].set(adapter),
            )

        if not self._kv_quant:

            def _import_handoff(
                ks, vs, ints, floats, bt_row, kp, vp, bt, state
            ):
                """Admit a prefilled KV handoff into a slot WITHOUT any
                prefill compute: scatter the (max_seq_len-padded) imported
                sequence through the freshly allocated block-table row and
                set the slot's decode state so the next decode step resumes
                exactly where the exporting engine's sampler left off.
                Positions >= length scatter into the reserved scratch
                page 0."""
                length = ints[0]
                page_ids, offsets = _seq_coords(bt_row, length, max_len, page)
                kp, vp = _scatter_seq(kp, vp, ks, vs, page_ids, offsets)
                bt = bt.at[ints[1]].set(bt_row)
                return kp, vp, bt, _slot_resume_state(state, ints, floats)

            self._import_handoff_jit = jax.jit(
                _import_handoff,
                donate_argnums=(5, 6),
                out_shardings=(
                    pool_sharding, pool_sharding, self._bt_sharding, None,
                ),
            )
        else:
            from kubeai_tpu.ops.paged_attention import (
                scatter_sequence_prequantized as _scatter_preq,
            )

            def _import_handoff_q(
                k8, ksc, v8, vsc, ints, floats, bt_row, kp, vp, bt, state
            ):
                """Quantized handoff import: the wire shipped int8 values
                + scales, and they scatter VERBATIM — re-quantizing a
                dequantized copy would round twice and break the
                byte-identity guarantee the disagg tests assert."""
                length = ints[0]
                page_ids, offsets = _seq_coords(bt_row, length, max_len, page)
                kp, vp = _scatter_preq(
                    kp, vp, k8, ksc, v8, vsc, page_ids, offsets
                )
                bt = bt.at[ints[1]].set(bt_row)
                return kp, vp, bt, _slot_resume_state(state, ints, floats)

            self._import_handoff_jit = jax.jit(
                _import_handoff_q,
                donate_argnums=(7, 8),
                out_shardings=(
                    pool_sharding, pool_sharding, self._bt_sharding, None,
                ),
            )

        if self._spec:
            gamma = self._spec
            if self._pp > 1:
                from functools import partial as _partial

                verify = _partial(
                    fam.decode_verify_paged_pp,
                    mesh=self.mesh,
                    microbatches=self._pp_microbatches,
                )
            else:
                verify = fam.decode_verify_paged

            def _spec_step(params, kp, vp, bt, state, proposals, lora):
                """One speculative step: verify [last_token, γ proposals]
                in a single forward; accept the longest prefix where the
                seeded sampler's choice equals the proposal; emit
                accepted+1 tokens. The emitted stream is bit-identical to
                vanilla decoding: choice k is sampled from the same
                logits with the same position fold it would see
                sequentially, and a mismatch truncates the window before
                any diverging context is used."""
                positions = state["positions"]
                seeds, temp = state["seeds"], state["temp"]
                topk, topp = state["topk"], state["topp"]
                tokens_in = jnp.concatenate(
                    [state["tokens"][:, None], proposals], axis=1
                )  # [B, γ+1]
                if lora is None:
                    logits, kp, vp = verify(
                        params, mcfg, tokens_in, positions, kp, vp, bt
                    )
                else:
                    logits, kp, vp = verify(
                        params, mcfg, tokens_in, positions, kp, vp, bt,
                        lora=lora, lora_idx=state["lora_idx"],
                    )
                choices = jnp.stack(
                    [
                        sample(
                            logits[:, k], seeds, positions + k + 1,
                            temp, topk, topp,
                        )
                        for k in range(gamma + 1)
                    ],
                    axis=1,
                )  # [B, γ+1]
                match = (choices[:, :gamma] == proposals).astype(jnp.int32)
                accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                n_emit = accepted + 1  # [B] in 1..γ+1
                new_pos = jnp.minimum(positions + n_emit, max_len - 1)
                last_tok = jnp.take_along_axis(
                    choices, accepted[:, None], axis=1
                )[:, 0]
                state = dict(
                    state, tokens=last_tok, positions=new_pos,
                )
                return choices, n_emit, kp, vp, state

            self._spec_jit = jax.jit(
                _spec_step,
                donate_argnums=(1, 2),
                out_shardings=(
                    None, None, pool_sharding, pool_sharding, None,
                ),
            )

        if self._draft:
            dcfg = self._draft_cfg
            gamma = self._spec
            dsh = self._draft_sharding
            decode_draft = fam.decode_step

            def _draft_propose(dparams, dk, dv, tokens, positions):
                """γ+1 greedy draft steps in ONE device call: the chain
                starts from the true last emitted token at its true
                position (keeping the draft's slot KV consistent — see
                Engine.__init__ docstring) and each step's argmax feeds
                the next. The chain runs one step PAST the last proposal
                so proposal γ's own KV is written too: on a fully
                accepted window that token is emitted and the next
                window resumes AFTER it — without the extra step its
                position would be a permanent hole in the draft cache,
                silently poisoning every later window's proposals.
                Returns proposals [B, γ] (the extra step's output is
                dropped)."""

                def step_fn(carry, _):
                    tok, pos, dk, dv = carry
                    logits, dk, dv = decode_draft(
                        dparams, dcfg, tok, pos, dk, dv
                    )
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    nxt_pos = jnp.minimum(pos + 1, max_len - 1)
                    return (nxt, nxt_pos, dk, dv), nxt

                (_, _, dk, dv), props = jax.lax.scan(
                    step_fn,
                    (tokens, positions, dk, dv),
                    None,
                    length=gamma + 1,
                )
                return jnp.moveaxis(props, 0, 1)[:, :gamma], dk, dv

            self._draft_propose_jit = jax.jit(
                _draft_propose,
                donate_argnums=(1, 2),
                out_shardings=(None, dsh, dsh),
            )

            draft_prefill = self._resolve_prefill()  # sp-aware, like target

            def _draft_admit(dparams, tokens, lengths, slots, dk, dv):
                """Draft prefill for an admission group: the draft's slot
                rows must hold the prompt KV before the first window
                (padding rows use slot = num_slots; the OOB scatter
                drops them)."""
                _, k_all, v_all = draft_prefill(
                    dparams, dcfg, tokens, lengths
                )
                S = tokens.shape[1]
                dk = dk.at[:, slots, :S].set(k_all.astype(dk.dtype))
                dv = dv.at[:, slots, :S].set(v_all.astype(dv.dtype))
                return dk, dv

            self._draft_admit_jit = jax.jit(
                _draft_admit,
                donate_argnums=(4, 5),
                out_shardings=(dsh, dsh),
            )

            def _draft_catchup(dparams, dk, dv, inputs, positions):
                """Teacher-forced draft pass over a chunk-mode window's
                emitted tokens. Adaptive switching runs whole windows in
                chunk mode, which advances sequences WITHOUT writing
                draft KV — without this pass the draft cache desyncs
                permanently after the first chunk window and acceptance
                silently collapses for the rest of each request's life.
                `inputs` is [chunk, B]: the pre-window last token, then
                the window's emitted tokens except its last (which is the
                next call's input)."""

                def step_fn(carry, tok):
                    pos, dk, dv = carry
                    _, dk, dv = decode_draft(dparams, dcfg, tok, pos, dk, dv)
                    return (jnp.minimum(pos + 1, max_len - 1), dk, dv), None

                (_, dk, dv), _ = jax.lax.scan(
                    step_fn, (positions, dk, dv), inputs
                )
                return dk, dv

            self._draft_catchup_jit = jax.jit(
                _draft_catchup,
                donate_argnums=(1, 2),
                out_shardings=(dsh, dsh),
            )

            if self.cfg.prefill_chunk > 0:
                draft_chunk_fn = self._chunk_fn

                def _dslot_slice(c, slot):
                    nl, _, L, kvh, d = c.shape
                    sl = jax.lax.dynamic_slice(
                        c, (0, slot, 0, 0, 0), (nl, 1, L, kvh, d)
                    )
                    return sl[:, 0]

                def _dslot_write(c, slot, sl):
                    return jax.lax.dynamic_update_slice(
                        c, sl[:, None].astype(c.dtype), (0, slot, 0, 0, 0)
                    )

                def _draft_chunk(dparams, tokens, ints, dk, dv):
                    """One chunk of draft prefill into the draft's slot
                    row — lets chunked/prefix-hit TARGET admissions keep
                    the draft cache in sync (the batched path's
                    whole-prompt _draft_admit can't serve them). `ints`
                    packs [start, length, slot]."""
                    start, length, slot = ints[0], ints[1], ints[2]
                    ks = _dslot_slice(dk, slot)
                    vs = _dslot_slice(dv, slot)
                    _, ks, vs = draft_chunk_fn(
                        dparams, dcfg, tokens, start, length, ks, vs,
                        want_logits=False,
                    )
                    return _dslot_write(dk, slot, ks), _dslot_write(dv, slot, vs)

                self._draft_chunk_jit = jax.jit(
                    _draft_chunk,
                    donate_argnums=(3, 4),
                    out_shardings=(dsh, dsh),
                )

        if self.cfg.prefill_chunk > 0:
            from kubeai_tpu.ops.paged_attention import (
                scatter_sequence,
                sequence_page_coords,
            )

            chunk_fn = self._chunk_fn
            stage_sharding = self._stage_sharding

            def _stage_mid(params, tokens, ints, ks, vs, lora):
                """One non-final chunk into the staging buffer. `ints`
                packs [start, length, adapter]."""
                start, length, adapter = ints[0], ints[1], ints[2]
                _, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=False,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                return ks, vs

            self._stage_chunk_mid_jit = jax.jit(
                _stage_mid,
                donate_argnums=(3, 4),
                out_shardings=(stage_sharding, stage_sharding),
            )

            def _stage_last(
                params, tokens, ints, floats, ks, vs, bt_row, kp, vp, bt,
                state, lora,
            ):
                """Final chunk: logits + staged-KV page scatter + first
                token + slot-state update in one device call. `ints`
                packs [start, length, slot, adapter, seed, top_k,
                forced]; forced >= 0 overrides the sample (preemption
                resume). Staged positions >= length scatter into the
                reserved scratch page 0."""
                start, length, slot = ints[0], ints[1], ints[2]
                adapter, seed = ints[3], ints[4]
                topk, forced = ints[5], ints[6]
                temp, topp = floats[0], floats[1]
                logits, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=True,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                page_ids, offsets = sequence_page_coords(
                    bt_row, length, max_len, page
                )
                kp, vp = scatter_sequence(kp, vp, ks, vs, page_ids, offsets)
                bt = bt.at[slot].set(bt_row)
                tok = sample(
                    logits,
                    seed.astype(jnp.uint32)[None],
                    length[None],
                    temp[None],
                    topk[None],
                    topp[None],
                )[0]
                tok = jnp.where(forced >= 0, forced, tok)
                state = dict(
                    tokens=state["tokens"].at[slot].set(tok),
                    positions=state["positions"].at[slot].set(length),
                    seeds=state["seeds"].at[slot].set(seed.astype(jnp.uint32)),
                    temp=state["temp"].at[slot].set(temp),
                    topk=state["topk"].at[slot].set(topk),
                    topp=state["topp"].at[slot].set(topp),
                    lora_idx=state["lora_idx"].at[slot].set(adapter),
                )
                return tok, ks, vs, kp, vp, bt, state

            self._stage_chunk_last_jit = jax.jit(
                _stage_last,
                donate_argnums=(4, 5, 7, 8, 9),
                out_shardings=(
                    None, stage_sharding, stage_sharding,
                    pool_sharding, pool_sharding, self._bt_sharding, None,
                ),
            )

            if self._prefix_cache:
                S = self.cfg.max_seq_len

                def _stage_from_pages(kp, vp, bt_row, ks, vs):
                    """Materialize a block-table row's pages into the
                    staging buffers (prefix-cache hit: the adopted prefix
                    becomes the context the suffix chunks attend over).
                    Static shapes: the whole row gathers every call;
                    junk past the cached length is masked by the chunk
                    graph's causal frontier and overwritten by the
                    suffix compute. Quantized pools dequantize into the
                    (bf16) staging buffers — the resident pages stay
                    byte-identical; only the staged working copy is
                    floating point."""
                    from kubeai_tpu.ops.kv_quant import (
                        dequantize_kv,
                        is_quantized_kv,
                    )

                    row = jnp.maximum(bt_row, 0)
                    if is_quantized_kv(kp):
                        gk = dequantize_kv(
                            kp["q8"][:, row], kp["scale"][:, row],
                            self.cfg.cache_dtype,
                        )
                        gv = dequantize_kv(
                            vp["q8"][:, row], vp["scale"][:, row],
                            self.cfg.cache_dtype,
                        )
                    else:
                        gk = kp[:, row]  # [NL, MP, page, KVH, D]
                        gv = vp[:, row]
                    nl, mp, pg, kvh, d = gk.shape
                    ks = gk.reshape(nl, mp * pg, kvh, d)[:, :S]
                    vs = gv.reshape(nl, mp * pg, kvh, d)[:, :S]
                    return ks.astype(self.cfg.cache_dtype), vs.astype(
                        self.cfg.cache_dtype
                    )

                self._stage_from_pages_jit = jax.jit(
                    _stage_from_pages,
                    donate_argnums=(3, 4),
                    out_shardings=(stage_sharding, stage_sharding),
                )

    # ---- public API ---------------------------------------------------------

    def add_request(
        self,
        prompt_tokens: list[int],
        params: SamplingParams | None = None,
        adapter: str | None = None,
        on_admit=None,
        priority: str | None = None,
        client: str = "",
        deadline_ms: float | None = None,
        resume_tokens: list[int] | None = None,
    ) -> int:
        """Queue a request. `on_admit(rid)` runs under the engine lock
        before the request becomes visible to `step()` — callers use it to
        register event subscribers without racing a concurrent serve loop
        (a request admitted and finished before registration would
        otherwise drop its events).

        Scheduling: `priority` is a class name (None = the scheduler
        policy's default), `client` the WFQ fairness key, `deadline_ms`
        an admission deadline — a deadline the scheduler judges
        infeasible given queue state and the measured drain rate raises
        `DeadlineInfeasible` and the request is NOT queued.

        Continuation: `resume_tokens` is a generation prefix already
        emitted by another replica (proxy stream resume after a
        preemption). The request admits through the same recompute path
        preemption uses — prefill prompt + prefix[:-1] with the first
        token FORCED to prefix[-1] — and step() emits only NEW tokens.
        Because the sampler is seeded and position-folded (stateless
        given (seed, position)), a seeded or greedy continuation is
        token-identical to the uninterrupted stream; unseeded sampling
        resumes with this replica's entropy and stays merely plausible."""
        params = params or SamplingParams()
        resume = [int(t) for t in (resume_tokens or [])]
        if resume:
            if len(resume) >= params.max_tokens:
                raise ValueError(
                    f"resume prefix of {len(resume)} tokens >= max_tokens "
                    f"{params.max_tokens}: nothing left to generate"
                )
            if len(prompt_tokens) + len(resume) >= self.cfg.max_seq_len:
                raise ValueError(
                    f"prompt + resume prefix length "
                    f"{len(prompt_tokens) + len(resume)} >= max_seq_len "
                    f"{self.cfg.max_seq_len}"
                )
            if resume[-1] in self.eos_token_ids:
                raise ValueError(
                    "resume prefix already ends at a stop token"
                )
        adapter_idx = 0
        if adapter:
            if self._lora is None:
                raise ValueError("LoRA is disabled (max_adapters=0)")
            if adapter not in self._adapter_slots:
                raise KeyError(f"adapter {adapter!r} not loaded")
            adapter_idx = self._adapter_slots[adapter]
        if len(prompt_tokens) == 0:
            raise ValueError("empty prompt")
        if len(prompt_tokens) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt_tokens)} >= max_seq_len {self.cfg.max_seq_len}"
            )
        with self._lock:
            if self._draining:
                raise EngineDraining("engine is draining")
            rid = self._next_rid
            self._next_rid += 1
            seed = (
                params.seed
                if params.seed is not None
                else (self._seed_base ^ rid)
            ) & 0xFFFFFFFF
            req = _Request(
                rid=rid,
                prompt=list(prompt_tokens),
                params=params,
                seed=seed,
                adapter_idx=adapter_idx,
                client=client,
                # A non-empty out_tokens prefix is what admission reads as
                # "resumed" — the same seat preemption re-admission uses.
                out_tokens=resume,
                stop_token_ids=self.eos_token_ids,
                t_enqueue=_now(),
            )
            self._requests[rid] = req
            if on_admit is not None:
                try:
                    on_admit(rid)
                except BaseException:
                    del self._requests[rid]
                    raise
            try:
                req.priority = self._sched.submit(
                    req,
                    priority=priority,
                    client=client,
                    deadline_ms=deadline_ms,
                )
            except BaseException:
                # Shed at enqueue (DeadlineInfeasible) or invalid
                # scheduling args: the request never becomes visible.
                del self._requests[rid]
                raise
            return rid

    def begin_drain(self) -> None:
        """Stop admitting new requests; queued + active work continues
        until finished (or the server's drain budget terminates it)."""
        with self._lock:
            # Overlap barrier: drain decisions (who is still running,
            # what to terminate) must see fully-reaped state.
            self._barrier_locked()
            self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def has_work(self) -> bool:
        return bool(len(self._sched) or self._active or self._inflight)

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def num_pending(self) -> int:
        return len(self._sched)

    @property
    def scheduler(self) -> RequestScheduler:
        """The request scheduler (queue-pressure snapshots, retry hints)."""
        return self._sched

    def drain_timing(self) -> list[tuple]:
        """Pop the accumulated latency observations: (kind, seconds) or
        (kind, seconds, exemplar_tag) with kind ∈ {queue_wait, prefill,
        ttft, itl, e2e} — ttft/itl carry a "rid-<n>" tag so the server's
        histograms keep a last-request exemplar per bucket. The serve
        loop (and the /metrics scrape) observes these into the server's
        histograms; draining transfers ownership so each record lands
        exactly once."""
        with self._lock:
            out, self._timing = self._timing, []
        return out

    def kv_utilization(self) -> float:
        """Fraction of KV-cache capacity in use: allocated pages over the
        pool (paged mode) or occupied token positions over total slot
        capacity (slot mode). Pages parked idle in the prefix cache count
        as free — they are reclaimable by any admission."""
        if self.cache_mode == "paged":
            total = self._n_pages - 1  # page 0 is reserved scratch
            if total <= 0:
                return 0.0
            return 1.0 - self._alloc.free_pages / total
        cap = self.cfg.num_slots * self.cfg.max_seq_len
        if cap <= 0:
            return 0.0
        return sum(r.position for r in self._active.values()) / cap

    def _bucket(self, n: int) -> int:
        for b in self.cfg.buckets():
            if n <= b:
                return b
        return self.cfg.max_seq_len

    def _pop_pending(self) -> _Request:
        """Dequeue the scheduler's next request for admission, stamping
        the moment it left the queue (queue-wait = this minus t_enqueue;
        prefill = first token minus this). A preempted request keeps its
        original stamp — its re-prefill is recompute, not a second queue
        wait."""
        req = self._sched.pop()
        if not req.t_admit_start:
            req.t_admit_start = _now()
        return req

    def _admit_pending(self) -> list[StepEvent]:
        """Prefill pending requests into free slots. Returns emitted tokens."""
        if self.cache_mode == "paged":
            return self._admit_pending_paged()
        emitted = []
        while len(self._sched) and self._free_slots:
            req = self._sched.peek()
            slot = self._free_slots[-1]
            # Resume (stream continuation / preemption recompute): the
            # prefix re-prefills as context with the last emitted token
            # FORCED — same contract as the paged path.
            resumed = bool(req.out_tokens)
            seq = (
                req.prompt + req.out_tokens[:-1] if resumed else req.prompt
            )
            plen = len(seq)
            self._pop_pending()
            self._free_slots.pop()
            req.slot = slot
            C = self.cfg.prefill_chunk
            if C > 0 and plen > C:
                tok = self._admit_chunked(req, slot, seq, plen, C)
                ev = self._finish_admission(req, slot, plen, tok, resumed)
                if ev is not None:
                    emitted.append(ev)
                continue
            bucket = self._bucket(plen)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :plen] = seq
            tok_dev, self.cache.k, self.cache.v, self._state = (
                self._prefill_admit_jit(
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(
                        [
                            plen,
                            slot,
                            # uint32 seed bit-cast into the int32 pack; the
                            # jit reinterprets it back via astype(uint32).
                            int(np.uint32(req.seed).view(np.int32)),
                            req.params.top_k,
                            req.adapter_idx,
                            req.out_tokens[-1] if resumed else -1,
                        ],
                        jnp.int32,
                    ),
                    jnp.asarray(
                        [req.params.temperature, req.params.top_p], jnp.float32
                    ),
                    self.cache.k,
                    self.cache.v,
                    self._state,
                    self._lora,
                )
            )
            ev = self._finish_admission(req, slot, plen, int(tok_dev), resumed)
            if ev is not None:
                emitted.append(ev)
        return emitted

    def _admit_pending_paged(self) -> list[StepEvent]:
        """Paged admission, BATCHED: same-bucket pending prompts prefill
        in one fused device call (up to cfg.max_admit_batch per call).
        A preempted request resumes by RECOMPUTE — re-prefill prompt +
        already-emitted tokens (minus the last, whose KV the next decode
        step writes) with its first token FORCED to the one already
        emitted."""
        from kubeai_tpu.engine.paged_cache import OutOfPages

        emitted: list[StepEvent] = []
        C = self.cfg.prefill_chunk
        while len(self._sched) and self._free_slots:
            batch: list[
                tuple[_Request, int, list[int], int, bool, list[bytes] | None]
            ] = []
            bucket = None
            chunked = None  # long prompt diverted to the staged-chunk path
            prefix_hit = None  # cached prefix diverted to the suffix path
            while (
                len(self._sched)
                and self._free_slots
                and len(batch) < max(1, self.cfg.max_admit_batch)
            ):
                req = self._sched.peek()
                resumed = bool(req.out_tokens)
                seq = (
                    req.prompt + req.out_tokens[:-1] if resumed
                    else req.prompt
                )
                plen = len(seq)
                hashes = None
                if self._prefix_cache and not resumed:
                    # Memoized per request: a head-of-line admission
                    # deferred by OutOfPages would otherwise re-hash its
                    # whole prompt every engine step. (Safe across steps:
                    # adapter swaps refuse while a pending request
                    # references the slot, so the generation in the seed
                    # cannot change under a queued request.)
                    hashes = getattr(req, "_apc_hashes", None)
                    if hashes is None:
                        hashes = self._prefix_hashes(seq, req.adapter_idx)
                        req._apc_hashes = hashes
                    # Cap the hit twice over: at least the final token
                    # must compute (its logits seed the first sample),
                    # and cached_len + prefill_chunk must fit inside the
                    # staging buffer — a padded suffix chunk starting
                    # past max_seq_len - C would have its
                    # dynamic_update_slice start CLAMPED, silently
                    # writing KV at the wrong offset and then scattering
                    # it into shared pages.
                    cap = min(
                        (plen - 1) // self.cfg.page_size,
                        max(
                            0,
                            (self.cfg.max_seq_len - C)
                            // self.cfg.page_size,
                        ),
                    )
                    hit = self._alloc.lookup(hashes[:cap])
                    if hit:
                        # One-at-a-time (staging buffer); flush any
                        # batch first and take the hit next iteration.
                        if not batch:
                            prefix_hit = (req, seq, plen, hashes, hit)
                        break
                if C > 0 and plen > C:
                    # Chunked admission is one-at-a-time (the staging
                    # buffer holds one sequence); flush any batch first.
                    if not batch:
                        chunked = (req, seq, plen, resumed, hashes)
                    break
                b = self._bucket(plen)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    break  # same-bucket batching only (no pad blow-up)
                slot = self._free_slots[-1]
                try:
                    pages = self._alloc.ensure(slot, plen)
                except OutOfPages:
                    break  # defer; ensure() rolled back
                self._pop_pending()
                self._free_slots.pop()
                req.slot = slot
                self._set_bt_row(slot, pages)
                batch.append((req, slot, seq, plen, resumed, hashes))
            if prefix_hit is not None:
                req, seq, plen, hashes, hit = prefix_hit
                slot = self._free_slots[-1]
                self._alloc.adopt(slot, hit)
                try:
                    pages = self._alloc.ensure(slot, plen)
                except OutOfPages:
                    self._alloc.unadopt(slot)
                    break  # defer; nothing held
                self._pop_pending()
                self._free_slots.pop()
                req.slot = slot
                self._set_bt_row(slot, pages)
                cached_len = len(hit) * self.cfg.page_size
                tok = self._admit_prefix_hit(req, slot, seq, plen, cached_len)
                self._note_prefix_admission(req, slot, plen, cached_len, hashes)
                ev = self._finish_admission(req, slot, plen, tok, False)
                if ev is not None:
                    emitted.append(ev)
                continue
            if chunked is not None:
                req, seq, plen, resumed, hashes = chunked
                slot = self._free_slots[-1]
                try:
                    pages = self._alloc.ensure(slot, plen)
                except OutOfPages:
                    break  # defer; ensure() rolled back
                self._pop_pending()
                self._free_slots.pop()
                req.slot = slot
                self._set_bt_row(slot, pages)
                tok = self._admit_chunked_paged(req, slot, seq, plen, C)
                if not resumed:
                    self._note_prefix_admission(req, slot, plen, 0, hashes)
                ev = self._finish_admission(req, slot, plen, tok, resumed)
                if ev is not None:
                    emitted.append(ev)
                continue
            if not batch:
                break
            toks = self._admit_paged_batch(batch, bucket)
            for (req, slot, _seq, plen, resumed, hashes), tok in zip(
                batch, toks
            ):
                if not resumed:
                    self._note_prefix_admission(req, slot, plen, 0, hashes)
                ev = self._finish_admission(req, slot, plen, int(tok), resumed)
                if ev is not None:
                    emitted.append(ev)
        return emitted

    def _prefix_hashes(self, tokens: list[int], adapter_idx: int) -> list[bytes]:
        """Page-aligned content-hash chain over a prompt. Seeded with the
        adapter slot AND its weight generation, so hot-swapping new
        weights into a reused adapter index can never hit stale KV."""
        import hashlib

        ps = self.cfg.page_size
        gen = self._adapter_gen.get(adapter_idx, 0)
        h = hashlib.blake2b(
            f"apc1:{adapter_idx}:{gen}".encode(), digest_size=16
        ).digest()
        arr = np.asarray(tokens, np.int32)
        out = []
        for i in range(len(tokens) // ps):
            h = hashlib.blake2b(
                h + arr[i * ps : (i + 1) * ps].tobytes(), digest_size=16
            ).digest()
            out.append(h)
        return out

    def _note_prefix_admission(
        self, req: _Request, slot: int, plen: int,
        cached_len: int, hashes: list[bytes] | None,
    ) -> None:
        """Account a fresh admission and publish its immutable full
        prompt pages (pages decode will never write: the first decode
        token lands at position plen, i.e. page plen // page_size).
        `hashes` is the chain the admission loop already computed (None
        when the prefix cache is off). Must run BEFORE _finish_admission
        — a request that finishes on its first token releases the slot
        there, and registration is what lets the released pages park in
        the cache."""
        if not self._prefix_cache or hashes is None:
            return
        self.prefix_stats["lookups"] += 1
        self.prefix_stats["hit_tokens"] += cached_len
        self.prefix_stats["prompt_tokens"] += plen
        n_reg = plen // self.cfg.page_size
        if n_reg == 0:
            return
        self._alloc.register(
            hashes[:n_reg], self._alloc.pages_for(slot)[:n_reg]
        )

    def _admit_prefix_hit(
        self, req: _Request, slot: int, seq: list[int], plen: int,
        cached_len: int,
    ) -> int:
        """Admission with an adopted cached prefix: materialize the
        prefix pages into the staging buffers, then prefill ONLY the
        suffix through the staged-chunk path (the final chunk scatters
        the staged sequence and samples the first token, exactly as
        chunked admission does)."""
        self._stage_k, self._stage_v = self._stage_from_pages_jit(
            self.cache.k_pages,
            self.cache.v_pages,
            jnp.asarray(self._bt_host[slot]),
            self._stage_k,
            self._stage_v,
        )
        C = self.cfg.prefill_chunk
        arr = np.asarray(seq, np.int32)
        mids = []
        s = cached_len
        while plen - s > C:
            mids.append((s, arr[None, s : s + C]))
            s += C
        # INVARIANT: no chunk may start before cached_len. The adopted
        # prefix pages are SHARED read-only; recomputing their positions
        # here would run a different XLA program than the one that
        # produced them (chunk graph vs bucketed prefill), and the final
        # chunk's scatter would then write not-bit-identical bf16 into
        # pages other requests are concurrently reading. Recompute
        # overlap is only safe WITHIN the suffix (same chunk graph,
        # deterministic), so short suffixes pad forward from cached_len
        # instead of back-aligning into the cached region. (The scatter
        # still rewrites the prefix pages, but with values GATHERED from
        # those very pages — bit-identical by construction.)
        if plen - cached_len >= C:
            last = (plen - C, arr[None, plen - C : plen])
        else:
            # The admission-loop hit cap guarantees this chunk fits the
            # staging buffer; a clamped dynamic_update_slice start would
            # write KV at the wrong offset and scatter it into shared
            # pages.
            assert cached_len + C <= self.cfg.max_seq_len, (
                cached_len, C, self.cfg.max_seq_len,
            )
            padded = np.zeros((1, C), np.int32)
            padded[0, : plen - cached_len] = arr[cached_len:plen]
            last = (cached_len, padded)
        self._draft_admit_chunked(seq, plen, slot)
        return self._run_staged_chunks(req, slot, plen, mids, last)

    def _admit_chunked_paged(
        self, req: _Request, slot: int, seq: list[int], plen: int, C: int
    ) -> int:
        """Chunked prefill in paged mode: chunks accumulate in the one-slot
        staging buffer; the final chunk scatters the whole staged sequence
        through the slot's freshly-allocated block-table row."""
        mids, last = self._chunk_plan(seq, plen, C)
        self._draft_admit_chunked(seq, plen, slot)
        return self._run_staged_chunks(req, slot, plen, mids, last)

    def _draft_admit_chunked(self, seq: list[int], plen: int, slot: int) -> None:
        """Chunk the whole prompt into the draft's slot row (the draft
        shares no pages with the target's prefix cache, so even a
        cache-hit admission prefills the draft over the FULL sequence —
        the draft is a fraction of the target's cost)."""
        if not self._draft:
            return
        C = self.cfg.prefill_chunk
        if plen >= C:
            mids, last = self._chunk_plan(seq, plen, C)
            chunks = [*mids, last]
        else:
            padded = np.zeros((1, C), np.int32)
            padded[0, :plen] = np.asarray(seq, np.int32)
            chunks = [(0, padded)]
        for start, tokens in chunks:
            self._dk, self._dv = self._draft_chunk_jit(
                self._draft_params,
                jnp.asarray(tokens),
                jnp.asarray([start, plen, slot], jnp.int32),
                self._dk,
                self._dv,
            )

    def _run_staged_chunks(
        self, req: _Request, slot: int, plen: int, mids, last
    ) -> int:
        """Run a staged-chunk schedule (mid chunks, then the scattering
        final chunk) — shared by chunked admission and prefix-cache-hit
        suffix prefill so the two paths cannot drift."""
        last_start, last_tokens = last
        for start, tokens in mids:
            self._stage_k, self._stage_v = self._stage_chunk_mid_jit(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray([start, plen, req.adapter_idx], jnp.int32),
                self._stage_k,
                self._stage_v,
                self._lora,
            )
        forced = req.out_tokens[-1] if req.out_tokens else -1
        (
            tok_dev,
            self._stage_k,
            self._stage_v,
            self.cache.k_pages,
            self.cache.v_pages,
            self.cache.block_tables,
            self._state,
        ) = self._stage_chunk_last_jit(
            self.params,
            jnp.asarray(last_tokens),
            jnp.asarray(
                [
                    last_start,
                    plen,
                    slot,
                    req.adapter_idx,
                    int(np.uint32(req.seed).view(np.int32)),
                    req.params.top_k,
                    forced,
                ],
                jnp.int32,
            ),
            jnp.asarray(
                [req.params.temperature, req.params.top_p], jnp.float32
            ),
            self._stage_k,
            self._stage_v,
            jnp.asarray(self._bt_host[slot]),
            self.cache.k_pages,
            self.cache.v_pages,
            self.cache.block_tables,
            self._state,
            self._lora,
        )
        return int(tok_dev)

    def _admit_paged_batch(self, batch, bucket: int) -> np.ndarray:
        A = len(batch)
        a_pad = 1
        while a_pad < A:
            a_pad *= 2
        mp = self._bt_host.shape[1]
        tokens = np.zeros((a_pad, bucket), np.int32)
        ints = np.zeros((a_pad, 6), np.int32)
        floats = np.zeros((a_pad, 2), np.float32)
        bt_rows = np.full((a_pad, mp), -1, np.int32)
        # Padding rows: length 1, slot out of range (scatter drops it),
        # bt_row -1 (page writes hit scratch), greedy sampling params.
        ints[:, 0] = 1
        ints[:, 1] = self.cfg.num_slots
        floats[:, 1] = 1.0
        for i, (req, slot, seq, plen, _resumed, _hashes) in enumerate(batch):
            tokens[i, :plen] = seq
            ints[i] = [
                plen,
                slot,
                int(np.uint32(req.seed).view(np.int32)),
                req.params.top_k,
                req.adapter_idx,
                # Resume: force the already-emitted last token instead
                # of trusting cross-kernel re-sampling determinism.
                req.out_tokens[-1] if req.out_tokens else -1,
            ]
            floats[i] = [req.params.temperature, req.params.top_p]
            bt_rows[i] = self._bt_host[slot]
        (
            toks_dev,
            self.cache.k_pages,
            self.cache.v_pages,
            self.cache.block_tables,
            self._state,
        ) = self._prefill_admit_jit(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(ints),
            jnp.asarray(floats),
            jnp.asarray(bt_rows),
            self.cache.k_pages,
            self.cache.v_pages,
            self.cache.block_tables,
            self._state,
            self._lora,
        )
        if self._draft:
            self._dk, self._dv = self._draft_admit_jit(
                self._draft_params,
                jnp.asarray(tokens),
                jnp.asarray(ints[:, 0]),
                jnp.asarray(ints[:, 1]),
                self._dk,
                self._dv,
            )
        return np.asarray(toks_dev)[:A]

    def _finish_admission(
        self, req: _Request, slot: int, plen: int, tok: int,
        resumed: bool = False,
    ) -> StepEvent | None:
        if resumed:
            if req.done:  # finished/cancelled while pending: don't revive
                self._release(req)
                return None
            # tok is the FORCED already-emitted last token; no new event.
            req.position = plen
            req.last_token = tok
            self._active[slot] = req
            return None
        # First token of a fresh admission: the whole front half of the
        # request lifecycle resolves here — queue wait (enqueue → dequeue),
        # prefill (dequeue → first token), TTFT (enqueue → first token).
        now = _now()
        self._timing.append(
            ("queue_wait", max(0.0, req.t_admit_start - req.t_enqueue))
        )
        self._timing.append(("prefill", max(0.0, now - req.t_admit_start)))
        self._timing.append(
            ("ttft", max(0.0, now - req.t_enqueue), f"rid-{req.rid}")
        )
        req.t_prev_token = now
        req.out_tokens.append(tok)
        req.position = plen
        req.last_token = tok
        finished = self._check_stop(req)
        if finished:
            self._release(req)
        else:
            self._active[slot] = req
        return StepEvent(req.rid, tok, finished, req.finish_reason)

    @staticmethod
    def _chunk_plan(seq: list[int], plen: int, C: int):
        """Chunk schedule: full-C mid chunks at 0, C, …; the FINAL chunk
        is aligned BACKWARD to end exactly at plen (start = plen - C), so
        its cache writes never reach past position plen —
        dynamic_update_slice would otherwise CLAMP the start index when
        ceil(plen/C)*C exceeds the buffer length and silently corrupt
        staged KV. Overlapping positions recompute byte-identical KV.
        Returns ([(start, tokens[1, C])...], (last_start, last_tokens))."""
        arr = np.asarray(seq, np.int32)
        n_chunks = -(-plen // C)
        mids = [
            (i * C, arr[None, i * C : (i + 1) * C])
            for i in range(n_chunks - 1)
        ]
        return mids, (plen - C, arr[None, plen - C : plen])

    def _admit_chunked(
        self, req: _Request, slot: int, seq: list[int], plen: int, C: int
    ) -> int:
        """Prefill a long prompt chunk-by-chunk into the slot cache; the
        final chunk also samples the first token and updates slot state.
        `seq` includes a resume prefix when the request is a continuation
        (the forced token then overrides the sample)."""
        mids, (last_start, last_tokens) = self._chunk_plan(seq, plen, C)
        for start, tokens in mids:
            self.cache.k, self.cache.v = self._prefill_chunk_mid_jit(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(
                    [start, slot, plen, req.adapter_idx], jnp.int32
                ),
                self.cache.k,
                self.cache.v,
                self._lora,
            )
        tok_dev, self.cache.k, self.cache.v, self._state = (
            self._prefill_chunk_last_jit(
                self.params,
                jnp.asarray(last_tokens),
                jnp.asarray(
                    [
                        last_start,
                        slot,
                        plen,
                        req.adapter_idx,
                        int(np.uint32(req.seed).view(np.int32)),
                        req.params.top_k,
                        req.out_tokens[-1] if req.out_tokens else -1,
                    ],
                    jnp.int32,
                ),
                jnp.asarray(
                    [req.params.temperature, req.params.top_p], jnp.float32
                ),
                self.cache.k,
                self.cache.v,
                self._state,
                self._lora,
            )
        )
        return int(tok_dev)

    def _check_stop(self, req: _Request) -> bool:
        if req.last_token in req.stop_token_ids:
            req.done = True
            req.finish_reason = "stop"
        elif len(req.out_tokens) >= req.params.max_tokens:
            req.done = True
            req.finish_reason = "length"
        elif req.position >= self.cfg.max_seq_len:
            # Next decode would write past the cache; the token just emitted
            # needed no cache slot, so capacity is fully used.
            req.done = True
            req.finish_reason = "length"
        return req.done

    def _decode_lookahead(self) -> int:
        """How far positions can advance in one device call. Adaptive
        speculation may run EITHER mode a given step, so cover both."""
        if self._spec:
            chunk = self._spec + 1
            if self.cfg.spec_adaptive:
                chunk = max(chunk, max(1, self.cfg.decode_chunk))
            return chunk
        return max(1, self.cfg.decode_chunk)

    def _ensure_decode_pages(self, inflight_lag: int = 0) -> None:
        """Grow every active slot's pages to cover the next decode chunk.
        Pool exhaustion preempts the YOUNGEST other request (recompute on
        re-admission). Init guarantees the pool holds one full sequence,
        so the loop always terminates with the oldest request served.

        `inflight_lag`: model steps of a dispatched-but-unreaped chunk.
        Host positions LAG the device by that many tokens while a chunk
        is in flight, so coverage extends past the lag or the overlapped
        dispatch would decode into unallocated rows of the block table."""
        from kubeai_tpu.engine.paged_cache import OutOfPages

        chunk = self._decode_lookahead() + max(0, int(inflight_lag))
        for slot, req in sorted(
            self._active.items(), key=lambda kv: kv[1].rid
        ):
            if self._active.get(slot) is not req:
                continue  # preempted by an earlier iteration of this loop
            need = min(req.position + chunk + 1, self.cfg.max_seq_len)
            while True:
                before = len(self._alloc.pages_for(slot))
                try:
                    pages = self._alloc.ensure(slot, need)
                except OutOfPages:
                    victims = [
                        r for r in self._active.values() if r is not req
                    ]
                    if not victims:  # cannot happen (init invariant)
                        raise
                    # Victim selection: lowest priority class first (a
                    # batch request must never evict a realtime one),
                    # youngest within a class (least progress lost).
                    self._preempt(max(
                        victims,
                        key=lambda r: (
                            CLASS_RANK.get(r.priority, 0), r.rid
                        ),
                    ))
                    continue
                break
            if len(pages) != before:
                self._set_bt_row(slot, pages)

    def _set_bt_row(self, slot: int, pages: list[int]) -> None:
        """Update the host block-table mirror for one slot and mark the
        device copy stale (pushed before the next decode dispatch)."""
        row = np.full((self._bt_host.shape[1],), -1, np.int32)
        row[: len(pages)] = pages
        self._bt_host[slot] = row
        self._bt_dirty = True

    def _preempt(self, victim: _Request) -> None:
        """Evict an active request: free its slot + pages, requeue it at
        the FRONT of pending for recompute re-admission (vLLM-style
        preemption, TPU-shaped: static graphs, host-side bookkeeping)."""
        slot = victim.slot
        self._active.pop(slot, None)
        self._free_slots.append(slot)
        victim.slot = -1
        self._alloc.release(slot)
        self._bt_host[slot] = -1
        self._bt_dirty = True
        self._sched.requeue_front(victim)
        # Optional observer (the server's flight recorder): set as a
        # plain attribute so engine stand-ins need no constructor change.
        cb = getattr(self, "on_preempt", None)
        if cb is not None:
            try:
                cb(victim.rid, victim.client)
            except Exception:
                pass

    def _release(self, req: _Request) -> None:
        # Completed requests (not cancellations — a disconnect says
        # nothing about generation latency) record their e2e duration.
        # t_enqueue doubles as the once-only flag: cancel() then a
        # resumed-done _finish_admission both land here.
        if req.finish_reason in ("stop", "length") and req.t_enqueue:
            self._timing.append(("e2e", max(0.0, _now() - req.t_enqueue)))
            req.t_enqueue = 0.0
        # A preempted request can finish (stop/cancel) while waiting in
        # the pending queue — drop it there too, or re-admission would
        # resurrect a done request that leaks its slot and pages forever.
        self._sched.remove(req)
        if req.slot >= 0:
            self._active.pop(req.slot, None)
            self._free_slots.append(req.slot)
            if self.cache_mode == "paged":
                # Free the pages and clear the row BEFORE the next decode:
                # a stale row would scatter the (junk) token of a freed
                # slot into pages that may now belong to a live sequence.
                self._alloc.release(req.slot)
                self._bt_host[req.slot] = -1
                self._bt_dirty = True
            req.slot = -1
        # Finished/cancelled requests leave the table immediately: callers
        # consume tokens from step() events, so retaining them would leak
        # (one _Request per request for the process lifetime).
        self._requests.pop(req.rid, None)

    def cancel(self, rid: int) -> bool:
        """Abort a request (pending or active). Safe mid-stream: the slot's
        stale KV is masked by per-slot lengths when the slot is reused."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return False
            # Overlap barrier: freeing the slot/pages under an unreaped
            # chunk would let admission reuse them before the reap; reap
            # first so the release mutates fully-settled state.
            self._barrier_locked()
            self._sched.remove(req)
            req.done = True
            req.finish_reason = "cancelled"
            self._release(req)
            return True

    # ---- disaggregated serving: KV handoff export / import ------------------

    def _kv_dtype_name(self) -> str:
        """Wire-format dtype name for KV exports ("int8" for quantized
        pools — the handoff/page-export headers carry it and importers
        refuse on mismatch rather than cast)."""
        return "int8" if self._kv_quant else np.dtype(self.cfg.cache_dtype).name

    def _gather_pages_host(self, pool, idx):
        """Gather pages[:, idx] to host. Returns (values, scales|None):
        quantized pools gather both leaves so exports ship the exact
        resident bytes (never a dequantized copy)."""
        from kubeai_tpu.ops.kv_quant import is_quantized_kv

        if is_quantized_kv(pool):
            return (
                np.asarray(jax.device_get(pool["q8"][:, idx])),
                np.asarray(jax.device_get(pool["scale"][:, idx])),
            )
        return np.asarray(jax.device_get(pool[:, idx])), None

    def _page_wire_nbytes(self) -> int:
        """Payload bytes of ONE page's K+V on the wire (scales included
        when quantized) — the unit every kv_share byte counter uses."""
        mcfg = self.model_cfg
        ps, kvh, d = self.cfg.page_size, mcfg.num_kv_heads, mcfg.head_size
        if self._kv_quant:
            return 2 * mcfg.num_layers * ps * kvh * (d + 4)
        return (
            2 * mcfg.num_layers * ps * kvh * d
            * np.dtype(self.cfg.cache_dtype).itemsize
        )

    def kv_cache_info(self) -> dict:
        """KV-cache capacity facts for /v1/state and the metrics plane:
        dtype, resident pool bytes, and the capacity factor vs a bf16
        pool at equal HBM (2D/(D+4) under int8 — what lets the
        autoscaler's KV-utilization signal and the capacity planner's
        right-sizing see the REAL slot capacity of a quantized replica)."""
        from kubeai_tpu.ops.kv_quant import kv_capacity_factor

        factor = (
            kv_capacity_factor(self.model_cfg.head_size)
            if self._kv_quant else 1.0
        )
        info = {
            "dtype": self._kv_dtype_name(),
            "quantized": self._kv_quant,
            "capacity_factor": factor,
            "slot_capacity": int(self.cfg.num_slots),
        }
        if self.cache_mode == "paged":
            info["num_pages"] = int(self._n_pages)
            info["page_size"] = int(self.cfg.page_size)
            info["token_capacity"] = int(
                (self._n_pages - 1) * self.cfg.page_size
            )
            info["pool_bytes"] = int(self.cache.nbytes())
        else:
            info["pool_bytes"] = int(
                self.cache.k.nbytes + self.cache.v.nbytes
            )
        return info

    def export_handoff(
        self,
        prompt_tokens: list[int],
        params: SamplingParams | None = None,
        adapter: str | None = None,
        client: str = "",
        priority: str = "",
        model_name: str = "",
    ):
        """Prefill-role serving: run (chunked) prefill for one request
        SYNCHRONOUSLY, sample its first token, and return a `KVHandoff`
        carrying the paged KV + sampling state — instead of entering
        decode. The slot and pages are borrowed only for the duration of
        this call; with the prefix cache enabled the prompt pages park in
        the idle pool on release, so repeated shared prefixes skip most
        of the prefill compute exactly as unified admission does.

        Raises EngineBusy when no slot/pages are free right now (the
        server sheds 429 and the router re-picks) and EngineDraining once
        drain has begun."""
        from kubeai_tpu.disagg.handoff import KVHandoff
        from kubeai_tpu.engine.paged_cache import OutOfPages

        if self.cache_mode != "paged":
            raise RuntimeError(
                "KV handoff export requires cache_mode='paged' (pages are "
                "the transfer unit)"
            )
        params = params or SamplingParams()
        adapter_idx = 0
        if adapter:
            if self._lora is None:
                raise ValueError("LoRA is disabled (max_adapters=0)")
            if adapter not in self._adapter_slots:
                raise KeyError(f"adapter {adapter!r} not loaded")
            adapter_idx = self._adapter_slots[adapter]
        seq = list(prompt_tokens)
        plen = len(seq)
        if plen == 0:
            raise ValueError("empty prompt")
        if plen >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {plen} >= max_seq_len {self.cfg.max_seq_len}"
            )
        with self._lock:
            # Overlap barrier: this borrows a slot + pages synchronously;
            # an unreaped chunk's stop-driven frees must land first.
            self._barrier_locked()
            if self._draining:
                raise EngineDraining("engine is draining")
            if not self._free_slots:
                raise EngineBusy("no free prefill slot")
            rid = self._next_rid
            self._next_rid += 1
            seed = (
                params.seed if params.seed is not None
                else (self._seed_base ^ rid)
            ) & 0xFFFFFFFF
            slot = self._free_slots.pop()
            try:
                pages = self._alloc.ensure(slot, plen)
            except OutOfPages:
                self._free_slots.append(slot)
                raise EngineBusy("KV page pool exhausted")
            try:
                self._set_bt_row(slot, pages)
                req = _Request(
                    rid=rid, prompt=seq, params=params, seed=seed,
                    adapter_idx=adapter_idx, client=client,
                    stop_token_ids=self.eos_token_ids,
                )
                t0 = _now()
                C = self.cfg.prefill_chunk
                hashes = self._prefix_hashes(seq, adapter_idx)
                if C > 0 and plen > C:
                    tok = self._admit_chunked_paged(req, slot, seq, plen, C)
                else:
                    tok = int(
                        self._admit_paged_batch(
                            [(req, slot, seq, plen, False, None)],
                            self._bucket(plen),
                        )[0]
                    )
                self._timing.append(("prefill", max(0.0, _now() - t0)))
                self._timing.append(
                    ("ttft", max(0.0, _now() - t0), f"rid-{rid}")
                )
                # Gather the sequence's pages to host IN TABLE ORDER: the
                # packed-page blob is position-major by construction.
                _kv_t0 = time.perf_counter()
                idx = jnp.asarray(pages, jnp.int32)
                k_host, k_scales = self._gather_pages_host(
                    self.cache.k_pages, idx
                )
                v_host, v_scales = self._gather_pages_host(
                    self.cache.v_pages, idx
                )
                self.profiler.observe(
                    "kv_transfer", time.perf_counter() - _kv_t0
                )
                if self._prefix_cache:
                    # Publish the prompt pages before release so they park
                    # in the idle LRU instead of returning to the free
                    # list — the prefill-pool half of prefix caching.
                    self._note_prefix_admission(req, slot, plen, 0, hashes)
            finally:
                self._alloc.release(slot)
                self._bt_host[slot] = -1
                self._bt_dirty = True
                self._free_slots.append(slot)
            first_finish = ""
            if tok in self.eos_token_ids:
                first_finish = "stop"
            elif params.max_tokens <= 1:
                first_finish = "length"
            handoff = KVHandoff(
                token_ids=seq,
                first_token=tok,
                first_finish=first_finish,
                page_size=self.cfg.page_size,
                dtype=self._kv_dtype_name(),
                k_pages=k_host,
                v_pages=v_host,
                k_scales=k_scales,
                v_scales=v_scales,
                seed=seed,
                temperature=params.temperature,
                top_k=params.top_k,
                top_p=params.top_p,
                max_tokens=params.max_tokens,
                stop=tuple(params.stop),
                prefix_hashes=tuple(h.hex() for h in hashes),
                adapter=adapter or "",
                client=client,
                priority=priority,
                model=model_name,
            )
            self.disagg_stats["exported"] += 1
            self.disagg_stats["exported_bytes"] += handoff.nbytes()
            return handoff

    def import_handoff(self, handoff, on_admit=None) -> tuple[int, StepEvent]:
        """Decode-role serving: admit a prefilled handoff DIRECTLY into a
        slot — scatter its KV through a fresh block-table row and set the
        slot's sampler state — bypassing every prefill graph. Returns
        (rid, first_event): the first token was sampled by the exporting
        engine, so the caller forwards `first_event` to its subscriber
        itself (step() only emits tokens decoded HERE). `on_admit(rid)`
        runs under the engine lock before the slot becomes visible to
        step(), exactly like add_request's hook.

        The decode stream is token-identical to a unified run: the pages
        hold bit-identical KV bytes, the slot state resumes the same
        seeded sampler at the same position, and decode runs the same
        compiled graph."""
        from kubeai_tpu.disagg.handoff import HandoffError

        if self.cache_mode != "paged":
            raise RuntimeError(
                "KV handoff import requires cache_mode='paged'"
            )
        mcfg = self.model_cfg
        nl, _n_pages, _page, kvh, d = handoff.k_pages.shape
        if (nl, kvh, d) != (
            mcfg.num_layers, mcfg.num_kv_heads, mcfg.head_size,
        ):
            raise HandoffError(
                f"handoff geometry [{nl}L,{kvh}KVH,{d}D] does not match "
                f"this model [{mcfg.num_layers}L,{mcfg.num_kv_heads}KVH,"
                f"{mcfg.head_size}D]"
            )
        plen = handoff.plen
        if plen >= self.cfg.max_seq_len:
            raise HandoffError(
                f"handoff length {plen} >= max_seq_len {self.cfg.max_seq_len}"
            )
        expect = self._kv_dtype_name()
        if handoff.dtype != expect or (
            self._kv_quant and not handoff.quantized
        ):
            # Refuse, never cast: an astype here would silently alter KV
            # values while the stream still claims token-identity with
            # the exporting engine.
            raise HandoffError(
                f"handoff KV dtype {handoff.dtype!r} != local pool dtype "
                f"{expect!r}; casting would break token-identity "
                "(re-export from a matching-dtype prefill pool)"
            )
        params = SamplingParams(
            temperature=handoff.temperature,
            top_k=handoff.top_k,
            top_p=handoff.top_p,
            max_tokens=handoff.max_tokens,
            seed=handoff.seed,
            stop=tuple(handoff.stop),
        )
        with self._lock:
            # Overlap barrier: handoff import admits a slot OUTSIDE
            # _admit_pending (bypassing step()'s admission barrier), so
            # reap here before the slot/page grant.
            self._barrier_locked()
            if self._draining:
                raise EngineDraining("engine is draining")
            adapter_idx = 0
            if handoff.adapter:
                if (
                    self._lora is None
                    or handoff.adapter not in self._adapter_slots
                ):
                    raise KeyError(
                        f"adapter {handoff.adapter!r} not loaded here"
                    )
                adapter_idx = self._adapter_slots[handoff.adapter]
            rid = self._next_rid
            self._next_rid += 1
            first_ev = StepEvent(
                rid, int(handoff.first_token),
                bool(handoff.first_finish), handoff.first_finish,
            )
            if handoff.first_finish:
                # Finished at its very first token: nothing to decode, no
                # slot to occupy — the caller just emits the final event.
                if on_admit is not None:
                    on_admit(rid)
                self.disagg_stats["imported"] += 1
                self.disagg_stats["imported_bytes"] += handoff.nbytes()
                return rid, first_ev
            if not self._free_slots:
                raise EngineBusy("no free decode slot")
            from kubeai_tpu.engine.paged_cache import OutOfPages

            slot = self._free_slots.pop()
            try:
                pages = self._alloc.ensure(slot, plen)
            except OutOfPages:
                self._free_slots.append(slot)
                raise EngineBusy("KV page pool exhausted")
            now = _now()
            req = _Request(
                rid=rid,
                prompt=list(handoff.token_ids),
                params=params,
                seed=handoff.seed,
                adapter_idx=adapter_idx,
                priority=handoff.priority or CLASS_STANDARD,
                client=handoff.client,
                out_tokens=[int(handoff.first_token)],
                slot=slot,
                position=plen,
                last_token=int(handoff.first_token),
                stop_token_ids=self.eos_token_ids,
                t_enqueue=now,
                t_admit_start=now,
                t_prev_token=now,
            )
            self._requests[rid] = req
            if on_admit is not None:
                try:
                    on_admit(rid)
                except BaseException:
                    del self._requests[rid]
                    self._alloc.release(slot)
                    self._free_slots.append(slot)
                    raise
            self._set_bt_row(slot, pages)
            # Re-page into THIS pool's layout: flatten to token order,
            # zero-pad to max_seq_len (the scatter's static shape) and
            # push through the import graph. Values are copied bit-exact
            # (a dtype mismatch was refused above, never cast).
            _kv_t0 = time.perf_counter()
            k_seq, v_seq = handoff.contiguous_kv()
            pad = np.zeros(
                (nl, self.cfg.max_seq_len, kvh, d), dtype=k_seq.dtype
            )
            k_pad, v_pad = pad.copy(), pad
            k_pad[:, :plen] = k_seq
            v_pad[:, :plen] = v_seq
            if self._kv_quant:
                ks_seq, vs_seq = handoff.contiguous_scales()
                spad = np.zeros(
                    (nl, self.cfg.max_seq_len, kvh), np.float32
                )
                ks_pad, vs_pad = spad.copy(), spad
                ks_pad[:, :plen] = ks_seq
                vs_pad[:, :plen] = vs_seq
            ints = jnp.asarray(
                [
                    plen,
                    slot,
                    int(np.uint32(handoff.seed & 0xFFFFFFFF).view(np.int32)),
                    params.top_k,
                    adapter_idx,
                    int(handoff.first_token),
                ],
                jnp.int32,
            )
            floats = jnp.asarray(
                [params.temperature, params.top_p], jnp.float32
            )
            if self._kv_quant:
                (
                    self.cache.k_pages,
                    self.cache.v_pages,
                    self.cache.block_tables,
                    self._state,
                ) = self._import_handoff_jit(
                    jnp.asarray(k_pad, jnp.int8),
                    jnp.asarray(ks_pad, jnp.float32),
                    jnp.asarray(v_pad, jnp.int8),
                    jnp.asarray(vs_pad, jnp.float32),
                    ints,
                    floats,
                    jnp.asarray(self._bt_host[slot]),
                    self.cache.k_pages,
                    self.cache.v_pages,
                    self.cache.block_tables,
                    self._state,
                )
            else:
                (
                    self.cache.k_pages,
                    self.cache.v_pages,
                    self.cache.block_tables,
                    self._state,
                ) = self._import_handoff_jit(
                    jnp.asarray(k_pad, self.cfg.cache_dtype),
                    jnp.asarray(v_pad, self.cfg.cache_dtype),
                    ints,
                    floats,
                    jnp.asarray(self._bt_host[slot]),
                    self.cache.k_pages,
                    self.cache.v_pages,
                    self.cache.block_tables,
                    self._state,
                )
            self.profiler.observe(
                "kv_transfer", time.perf_counter() - _kv_t0
            )
            # _set_bt_row marked the host mirror dirty; the import graph
            # also set the device row, so the next step's device_put is
            # redundant but harmless (and still needed if OTHER slots'
            # rows changed since the last dispatch).
            if self._prefix_cache and handoff.prefix_hashes:
                n_reg = min(
                    plen // self.cfg.page_size, len(handoff.prefix_hashes)
                )
                if n_reg > 0:
                    self._alloc.register(
                        [bytes.fromhex(h) for h in
                         handoff.prefix_hashes[:n_reg]],
                        pages[:n_reg],
                    )
            self._active[slot] = req
            self.disagg_stats["imported"] += 1
            self.disagg_stats["imported_bytes"] += handoff.nbytes()
            return rid, first_ev

    # ---- cluster KV-sharing tier ------------------------------------------

    def prefix_holdings(self) -> list[str]:
        """Every chain hash (hex) this replica's prefix cache currently
        holds — published via /v1/state so the fleet aggregator can build
        the who-holds-which-prefix map. Advisory: routing hints built on
        it can go stale without harming correctness (admission re-checks
        through lookup())."""
        if self.cache_mode != "paged" or not self._prefix_cache:
            return []
        with self._lock:
            return [h.hex() for h in self._alloc.holdings()]

    def cached_prefix_depth(self, hashes_hex: list[str]) -> int:
        """How many leading pages of the chain are held locally right
        now — what a peer fetch would NOT need to transfer."""
        if self.cache_mode != "paged" or not self._prefix_cache:
            return 0
        try:
            hashes = [bytes.fromhex(h) for h in hashes_hex]
        except ValueError:
            return 0
        with self._lock:
            return len(self._alloc.lookup(hashes))

    def compute_prefix_chain(self, tokens: list[int]) -> list[str]:
        """Base-model page-hash chain (hex) for a token sequence — the
        engine-side oracle the front door's chain computation must match."""
        return [h.hex() for h in self._prefix_hashes(list(tokens), 0)]

    def export_prefix_pages(self, hashes_hex: list[str], max_bytes: int = 0):
        """Serve a peer's partial-chain fetch: gather the longest locally
        held prefix of the requested chain (optionally truncated to a
        transfer-size cap) to host and wrap it as a `KVPageExport`. Pages
        are copied under the engine lock, so the bytes are a consistent
        snapshot; an empty export means "hold nothing of that chain".
        Base-model chains only — per-replica LoRA slot seeds make adapter
        chains incomparable across replicas."""
        from kubeai_tpu.disagg.handoff import KVPageExport

        if self.cache_mode != "paged" or not self._prefix_cache:
            return None
        try:
            hashes = [bytes.fromhex(h) for h in hashes_hex]
        except ValueError:
            return None
        mcfg = self.model_cfg
        ps = self.cfg.page_size
        page_nbytes = self._page_wire_nbytes()
        with self._lock:
            # Overlap barrier: the exported bytes must be a settled
            # snapshot — an in-flight chunk is still WRITING pages.
            self._barrier_locked()
            pages = self._alloc.lookup(hashes)
            if max_bytes > 0:
                pages = pages[: max_bytes // page_nbytes]
            n = len(pages)
            k_scales = v_scales = None
            if n:
                idx = jnp.asarray(pages, jnp.int32)
                k_host, k_scales = self._gather_pages_host(
                    self.cache.k_pages, idx
                )
                v_host, v_scales = self._gather_pages_host(
                    self.cache.v_pages, idx
                )
            else:
                shape = (
                    mcfg.num_layers, 0, ps, mcfg.num_kv_heads, mcfg.head_size,
                )
                if self._kv_quant:
                    k_host = np.zeros(shape, np.int8)
                    v_host = np.zeros(shape, np.int8)
                    k_scales = np.zeros(shape[:-1], np.float32)
                    v_scales = np.zeros(shape[:-1], np.float32)
                else:
                    dtype = np.dtype(self.cfg.cache_dtype)
                    k_host = np.zeros(shape, dtype)
                    v_host = np.zeros(shape, dtype)
            self.kv_share_stats["exported_pages"] += n
            self.kv_share_stats["exported_bytes"] += n * page_nbytes
        return KVPageExport(
            prefix_hashes=tuple(hashes_hex[:n]),
            page_size=ps,
            dtype=self._kv_dtype_name(),
            k_pages=k_host,
            v_pages=v_host,
            k_scales=k_scales,
            v_scales=v_scales,
        )

    def import_prefix_pages(self, export, source: str = "peer") -> int:
        """Seed fetched prefix pages into the idle pool, unowned: the next
        admission whose chain matches adopts them through the ordinary
        lookup()/adopt() path, so a stale or partial import can only cost
        recompute, never correctness. Geometry, page size AND dtype must
        match exactly — a cast would alter KV values while the chain hash
        still vouches for the original content, silently breaking
        token-identity with the no-sharing baseline. Returns the number of
        pages actually seeded (0 when the pool refuses or everything was
        already held)."""
        from kubeai_tpu.disagg.handoff import HandoffError

        if self.cache_mode != "paged" or not self._prefix_cache:
            return 0
        if export.n_pages == 0:
            return 0
        mcfg = self.model_cfg
        nl, _n, page, kvh, d = export.k_pages.shape
        if (nl, kvh, d) != (
            mcfg.num_layers, mcfg.num_kv_heads, mcfg.head_size,
        ):
            raise HandoffError(
                f"page export geometry [{nl}L,{kvh}KVH,{d}D] does not "
                f"match this model [{mcfg.num_layers}L,"
                f"{mcfg.num_kv_heads}KVH,{mcfg.head_size}D]"
            )
        if page != self.cfg.page_size:
            raise HandoffError(
                f"page size {page} != local {self.cfg.page_size} (chain "
                "hashes are page-size-dependent; no re-paging is possible)"
            )
        if export.dtype != self._kv_dtype_name() or (
            self._kv_quant and not export.quantized
        ):
            raise HandoffError(
                f"KV dtype {export.dtype} != local cache dtype "
                f"{self._kv_dtype_name()}; casting would "
                "break token-identity"
            )
        try:
            hashes = [bytes.fromhex(h) for h in export.prefix_hashes]
        except ValueError as e:
            raise HandoffError(f"bad chain hash: {e}") from e
        with self._lock:
            # Overlap barrier: seeding idle-pool pages races an unreaped
            # chunk's frees/allocations — reap before touching the pool.
            self._barrier_locked()
            seeded = self._alloc.seed_unowned(hashes)
            if seeded is None:
                return 0
            write = [(i, p) for i, p in enumerate(seeded) if p is not None]
            if write:
                idx = jnp.asarray([p for _, p in write], jnp.int32)
                cols = [i for i, _ in write]
                if self._kv_quant:
                    # Verbatim int8 + scale writes — the chain hash
                    # vouches for these exact quantized bytes.
                    self.cache.k_pages = {
                        "q8": self.cache.k_pages["q8"].at[:, idx].set(
                            jnp.asarray(
                                np.ascontiguousarray(
                                    export.k_pages[:, cols]
                                ),
                                jnp.int8,
                            )
                        ),
                        "scale": self.cache.k_pages["scale"].at[:, idx].set(
                            jnp.asarray(
                                np.ascontiguousarray(
                                    export.k_scales[:, cols]
                                ),
                                jnp.float32,
                            )
                        ),
                    }
                    self.cache.v_pages = {
                        "q8": self.cache.v_pages["q8"].at[:, idx].set(
                            jnp.asarray(
                                np.ascontiguousarray(
                                    export.v_pages[:, cols]
                                ),
                                jnp.int8,
                            )
                        ),
                        "scale": self.cache.v_pages["scale"].at[:, idx].set(
                            jnp.asarray(
                                np.ascontiguousarray(
                                    export.v_scales[:, cols]
                                ),
                                jnp.float32,
                            )
                        ),
                    }
                else:
                    src = np.ascontiguousarray(export.k_pages[:, cols])
                    self.cache.k_pages = self.cache.k_pages.at[:, idx].set(
                        jnp.asarray(src, self.cfg.cache_dtype)
                    )
                    src = np.ascontiguousarray(export.v_pages[:, cols])
                    self.cache.v_pages = self.cache.v_pages.at[:, idx].set(
                        jnp.asarray(src, self.cfg.cache_dtype)
                    )
            key = "imported_pages" if source == "peer" else "filled_pages"
            self.kv_share_stats[key] += len(write)
            if source == "peer":
                self.kv_share_stats["imported_bytes"] += (
                    len(write) * self._page_wire_nbytes()
                )
            return len(write)

    def enable_kv_spill(self, store) -> None:
        """Wire idle-pool eviction to an objstore spill: just before an
        evicted page's registration is destroyed, its K/V bytes are
        snapshotted to `store` keyed by the chain hash, so a later fetch
        for an evicted hot prefix can FILL from the store instead of
        recomputing. The hook runs under the engine lock on the eviction
        path and must never raise (the allocator also guards it)."""
        from kubeai_tpu.disagg.handoff import KVPageExport, serialize_pages

        def _spill(page: int, h: bytes) -> None:
            idx = jnp.asarray([page], jnp.int32)
            k, k_scales = self._gather_pages_host(self.cache.k_pages, idx)
            v, v_scales = self._gather_pages_host(self.cache.v_pages, idx)
            blob = serialize_pages(
                KVPageExport(
                    prefix_hashes=(h.hex(),),
                    page_size=self.cfg.page_size,
                    dtype=self._kv_dtype_name(),
                    k_pages=k,
                    v_pages=v,
                    k_scales=k_scales,
                    v_scales=v_scales,
                )
            )
            store.put(h.hex(), blob)
            self.kv_share_stats["spilled_pages"] += 1

        self._alloc.on_evict = _spill

    def _spec_pick(self) -> bool:
        """Choose this decode call's mode (True = speculative window,
        False = fused chunk). Epsilon-greedy over measured tokens/s:
        sample each arm once, then run the winner, re-probing the loser
        every cfg.spec_probe_every calls so a workload shift (e.g. the
        batch turning repetitive) is noticed.

        Stream-stability caveat: mode invariance relies on both compiled
        graphs producing the same sampled tokens. Greedy (temperature=0)
        decoding is exactly mode-invariant (verify accepts iff tokens
        match argmax). With temperature>0 the seeded sampler consumes the
        same per-slot key sequence in both modes, but the two graphs may
        differ in logits by ULPs on TPU, so a near-tie sample can flip at
        a mode switch. That is within the API contract (sampling makes no
        cross-process bitwise guarantee) but means tests asserting exact
        seeded streams run on one mode; set spec_adaptive=False when
        bitwise-stable seeded streams matter."""
        if not self.cfg.spec_adaptive:
            return True
        self._decode_calls += 1
        s = self._mode_tps.get("spec")
        c = self._mode_tps.get("chunk")
        if self._mode_calls.get("spec", 0) < 2:
            return True
        if self._mode_calls.get("chunk", 0) < 2:
            return False
        if self._decode_calls % max(2, self.cfg.spec_probe_every) == 0:
            return s <= c  # probe the currently losing arm
        return s > c

    def _spec_observe(self, mode: str, tokens: int, dt: float) -> None:
        """Fold one decode call's throughput into the mode's EMA. The
        first call per mode is counted but not folded — it includes
        compile time and would poison the estimate."""
        calls = self._mode_calls.get(mode, 0) + 1
        self._mode_calls[mode] = calls
        if calls < 2 or dt <= 0 or tokens <= 0:
            return
        tps = tokens / dt
        prev = self._mode_tps.get(mode)
        self._mode_tps[mode] = (
            tps if prev is None else 0.7 * prev + 0.3 * tps
        )

    def step(self) -> list[StepEvent]:
        """Admit pending prefills, then run one fused decode chunk
        (cfg.decode_chunk model steps in a single device call).

        With step_overlap resolved on, the chunk dispatched this call is
        reaped on the NEXT call: the device computes chunk N+1 while the
        host reads back and processes chunk N's tokens (readback,
        admission, detokenize, SSE fan-out all hide behind device
        compute). Conservative barriers reap first wherever overlap
        could change tokens — see _reap_inflight_locked.

        Returns a list of StepEvents in emission order.
        """
        with self._lock:
            # Per-phase timeline for this step (fleet/profiler.py):
            # prefill = admission pass, schedule = host bookkeeping
            # before the decode dispatch, dispatch = block-table upload,
            # decode = jit DISPATCH (async; the device wait lands in
            # overlap_idle and the transfer in readback inside
            # _process_chunk), sample = host token emission.
            phases: dict[str, float] = {}
            self._phase_scratch = phases
            emitted: list[StepEvent] = []
            if self._pending_events:
                # Tokens reaped by an out-of-step barrier (cancel, drain,
                # handoff, prefix fetch) — deliver before this step's.
                emitted.extend(self._pending_events)
                self._pending_events.clear()
            # ADMISSION BARRIER: a pending prompt's slot/page grant must
            # observe the in-flight chunk's stop-driven slot frees (and a
            # preempted request's re-prefill must see its full out_tokens),
            # so reap before admitting. Also reap before any speculation
            # window: prompt-lookup proposals read out_tokens.
            if self._inflight is not None and (len(self._sched) or self._spec):
                emitted.extend(self._reap_inflight_locked())
            _admit_t0 = time.perf_counter()
            emitted.extend(self._admit_pending())
            phases["prefill"] = (
                phases.get("prefill", 0.0)
                + (time.perf_counter() - _admit_t0)
            )
            prev = self._inflight
            self._inflight = None
            current = None
            decode_mode = None
            t0 = time.perf_counter()
            _dec_t0 = t0
            if self._active and prev is not None:
                # SEQ-CAP BARRIER: dispatching chunk N+1 before reaping N
                # advances device positions by up to len(N) + chunk. If
                # any slot could cross max_seq_len in that window its
                # decode would write past its block-table row, so reap
                # first — the dispatch below then overshoots by at most
                # one chunk, exactly the envelope the synchronous loop
                # already tolerates (surplus tokens are discarded).
                horizon = prev[2] + self._decode_lookahead() + 1
                if any(
                    req.position + horizon >= self.cfg.max_seq_len
                    for req in self._active.values()
                ):
                    emitted.extend(self._process_chunk(prev))
                    prev = None
            if self._active:
                if self.cache_mode == "paged":
                    self._ensure_decode_pages(
                        inflight_lag=prev[2] if prev is not None else 0
                    )
                    if self._bt_dirty:
                        _disp_t0 = time.perf_counter()
                        self.cache.block_tables = jax.device_put(
                            jnp.asarray(self._bt_host), self._bt_sharding
                        )
                        self._bt_dirty = False
                        self._note_phase(
                            "dispatch", time.perf_counter() - _disp_t0
                        )
                    _dec_t0 = time.perf_counter()
                    phases["schedule"] = (
                        _dec_t0 - t0 - phases.get("dispatch", 0.0)
                    )
                    if self._spec and self._spec_pick():
                        decode_mode = "spec"
                        if self._draft:
                            proposals, self._dk, self._dv = (
                                self._draft_propose_jit(
                                    self._draft_params,
                                    self._dk,
                                    self._dv,
                                    self._state["tokens"],
                                    self._state["positions"],
                                )
                            )
                        else:
                            proposals = jnp.asarray(self._build_proposals())
                        (
                            choices,
                            n_emit,
                            self.cache.k_pages,
                            self.cache.v_pages,
                            self._state,
                        ) = self._spec_jit(
                            self.params,
                            self.cache.k_pages,
                            self.cache.v_pages,
                            self.cache.block_tables,
                            self._state,
                            proposals,
                            self._lora,
                        )
                        toks_seq = ("spec", choices, n_emit)
                    else:
                        if self._spec:
                            decode_mode = "chunk"
                        pre_tokens = pre_positions = None
                        if self._draft:
                            pre_tokens = self._state["tokens"]
                            pre_positions = self._state["positions"]
                        (
                            toks_seq,
                            self.cache.k_pages,
                            self.cache.v_pages,
                            self._state,
                        ) = self._decode_jit(
                            self.params,
                            self.cache.k_pages,
                            self.cache.v_pages,
                            self.cache.block_tables,
                            self._state,
                            self._lora,
                        )
                        if self._draft:
                            # Keep the draft cache in lockstep with the
                            # chunk the target just decoded (see
                            # _draft_catchup).
                            inputs = jnp.concatenate(
                                [pre_tokens[None], toks_seq[:-1]], axis=0
                            )
                            self._dk, self._dv = self._draft_catchup_jit(
                                self._draft_params, self._dk, self._dv,
                                inputs, pre_positions,
                            )
                else:
                    _dec_t0 = time.perf_counter()
                    toks_seq, self.cache.k, self.cache.v, self._state = (
                        self._decode_jit(
                            self.params, self.cache.k, self.cache.v,
                            self._state, self._lora,
                        )
                    )
                phases["decode"] = (
                    phases.get("decode", 0.0)
                    + (time.perf_counter() - _dec_t0)
                )
                self._steps += 1
                is_spec = isinstance(toks_seq, tuple)
                chunk_len = 0 if is_spec else int(toks_seq.shape[0])
                current = (
                    toks_seq,
                    list(self._active.items()),
                    chunk_len,
                    time.monotonic(),
                )
                if self._overlap and not is_spec and not self._spec:
                    # Reap current NEXT call: the device computes through
                    # the host's readback+process of prev. Speculation
                    # windows never overlap — proposals read out_tokens,
                    # and the adaptive arm needs the measured wall time of
                    # every chunk call.
                    self._inflight = current
                    current = None
            if prev is not None:
                emitted.extend(self._process_chunk(prev))
            if current is not None:
                evs = self._process_chunk(current)
                emitted.extend(evs)
                if decode_mode is not None:
                    # Wall time covers dispatch + device + fetch — exactly
                    # the cost the mode choice trades off.
                    self._spec_observe(
                        decode_mode, len(evs), time.perf_counter() - t0
                    )
            step_s = time.perf_counter() - t0
            # Feed the scheduler's drain-rate estimator: completed
            # requests per second of engine-step wall time. Deadline
            # feasibility and the computed Retry-After both divide queue
            # depth by this rate.
            finished = sum(1 for ev in emitted if ev.finished)
            self._sched.observe_service(finished, step_s)
            # Per-decode-step snapshot for the serve loop's gauges. Plain
            # attribute write (already under the engine lock): the metrics
            # registry is never touched from this hot path. The overlap
            # tail — reaping a chunk whose every row finished last step,
            # emitting nothing, with no work left — must not clobber the
            # final real step's numbers with zeros.
            if (
                emitted
                or self._active
                or len(self._sched)
                or current is not None
                or self._inflight is not None
            ):
                self.last_step_stats = {
                    "batch_size": len(self._active),
                    "waiting": len(self._sched),
                    "tokens": len(emitted),
                    "duration_s": step_s,
                }
            self._phase_scratch = None
            # Record only steps that DID something — an idle poll's
            # all-zero timeline would just dilute the ring. A dispatch-
            # only step (overlap holding its first chunk) counts.
            if (
                emitted
                or current is not None
                or prev is not None
                or self._inflight is not None
            ):
                self.profiler.observe_step(
                    phases,
                    tokens=len(emitted),
                    batch=len(self._active),
                    duration_s=step_s,
                )
            return emitted

    def _reap_inflight_locked(self) -> list[StepEvent]:
        """Reap the dispatched-but-unreaped chunk NOW (caller holds the
        engine lock). The conservative barrier behind every mutation that
        must observe the chunk's tokens or slot frees: pending
        admissions, cancel, drain, handoff export/import, prefix-page
        export/import, speculation windows. Returns the chunk's events."""
        inflight = self._inflight
        if inflight is None:
            return []
        self._inflight = None
        return self._process_chunk(inflight)

    def _barrier_locked(self) -> None:
        """Barrier for callers OUTSIDE step() (cancel/drain/handoff/
        prefix paths, under the engine lock): reap the in-flight chunk
        and queue its events for the next step() so no token is lost."""
        evs = self._reap_inflight_locked()
        if evs:
            self._pending_events.extend(evs)

    def inflight_info(self) -> dict | None:
        """Snapshot of the dispatched-but-unreaped chunk for the server
        watchdog: {"dispatched_at": monotonic seconds} or None. Lock-free
        read of an atomically swapped tuple — safe from the watchdog
        thread."""
        inflight = self._inflight
        if inflight is None or len(inflight) < 4:
            return None
        return {"dispatched_at": inflight[3]}

    def _process_chunk(self, inflight: tuple) -> list[StepEvent]:
        toks_seq, chunk_slots = inflight[0], inflight[1]
        if isinstance(toks_seq, tuple) and toks_seq[0] == "spec":
            return self._process_spec(toks_seq[1], toks_seq[2], chunk_slots)
        cols = [slot for slot, req in chunk_slots if not req.done]
        if not cols:
            return []  # every rider cancelled since dispatch — no transfer
        col_of = None
        if len(cols) < int(toks_seq.shape[1]):
            # Slice to the ACTIVE rows on-device before the host
            # transfer: the decode chunk is a padded [chunk, B] buffer
            # and fetching dead columns ships chunk*(B-A) junk tokens
            # per step. The gather is a dependent device op, so timing
            # block_until_ready on its output still measures the chunk's
            # compute wait.
            toks_seq = jnp.take(
                toks_seq, jnp.asarray(cols, jnp.int32), axis=1
            )
            col_of = {slot: i for i, slot in enumerate(cols)}
        _wait_t0 = time.perf_counter()
        toks_seq = jax.block_until_ready(toks_seq)
        # Device compute the host could NOT hide: ~the whole device step
        # in the synchronous loop, →0 under perfect overlap.
        self._note_phase("overlap_idle", time.perf_counter() - _wait_t0)
        _sync_t0 = time.perf_counter()
        toks_seq = np.asarray(jax.device_get(toks_seq))  # [chunk, A]
        self._note_phase("readback", time.perf_counter() - _sync_t0)
        _sample_t0 = time.perf_counter()
        emitted: list[StepEvent] = []
        for k in range(toks_seq.shape[0]):
            # One timestamp per fused decode step: its tokens became
            # host-visible together, so intra-step ITL is genuinely ~0 and
            # the first token after a chunk boundary carries the gap.
            now = _now()
            for slot, req in chunk_slots:
                if req.done:
                    continue  # surplus chunk tokens discarded
                tok = int(
                    toks_seq[k, slot if col_of is None else col_of[slot]]
                )
                if req.t_prev_token:
                    self._timing.append(
                        ("itl", max(0.0, now - req.t_prev_token),
                         f"rid-{req.rid}")
                    )
                req.t_prev_token = now
                req.out_tokens.append(tok)
                req.position += 1
                req.last_token = tok
                finished = self._check_stop(req)
                emitted.append(
                    StepEvent(req.rid, tok, finished, req.finish_reason)
                )
                if finished:
                    self._release(req)
        self._note_phase("sample", time.perf_counter() - _sample_t0)
        return emitted

    def _note_phase(self, phase: str, seconds: float) -> None:
        """Accumulate a phase duration into the CURRENT step's timeline
        (no-op outside step(); always under the engine lock)."""
        ph = self._phase_scratch
        if ph is not None:
            ph[phase] = ph.get(phase, 0.0) + seconds

    def _process_spec(
        self, choices, n_emit, chunk_slots
    ) -> list[StepEvent]:
        """Emit each slot's accepted+corrected tokens (1..γ+1 per step).
        A stop mid-window discards the remainder, like chunk surplus."""
        _sync_t0 = time.perf_counter()
        # ONE fused transfer for both outputs: two sequential device_get
        # calls would pay the host round trip twice per verify step and
        # charge readback for both (a profiler test pins this to one).
        choices, n_emit = jax.device_get((choices, n_emit))
        choices = np.asarray(choices)  # [B, γ+1]
        n_emit = np.asarray(n_emit)  # [B]
        self._note_phase("readback", time.perf_counter() - _sync_t0)
        _sample_t0 = time.perf_counter()
        emitted: list[StepEvent] = []
        now = _now()  # one verify forward produced the whole window
        for slot, req in chunk_slots:
            if req.done:
                continue
            self.spec_stats["windows"] += 1
            self.spec_stats["proposed"] += self._spec
            self.spec_stats["accepted"] += int(n_emit[slot]) - 1
            for j in range(int(n_emit[slot])):
                tok = int(choices[slot, j])
                if req.t_prev_token:
                    self._timing.append(
                        ("itl", max(0.0, now - req.t_prev_token),
                         f"rid-{req.rid}")
                    )
                req.t_prev_token = now
                req.out_tokens.append(tok)
                req.position += 1
                req.last_token = tok
                finished = self._check_stop(req)
                emitted.append(
                    StepEvent(req.rid, tok, finished, req.finish_reason)
                )
                if finished:
                    self._release(req)
                    break
        self._note_phase("sample", time.perf_counter() - _sample_t0)
        return emitted

    def _build_proposals(self) -> np.ndarray:
        """Prompt-lookup proposals [num_slots, γ]: the longest suffix
        n-gram (n = 3, 2, 1) of each active request's context that
        occurred earlier proposes its historical continuation (inactive
        slots get zeros; their results are discarded anyway). Contexts
        are kept in per-request incremental buffers — only newly emitted
        tokens append each step."""
        gamma = self._spec
        out = np.zeros((self.cfg.num_slots, gamma), np.int32)
        for slot, req in self._active.items():
            need = len(req.prompt) + len(req.out_tokens)
            if req.ctx is None or need < req.ctx_len:
                req.ctx = np.empty(
                    self.cfg.max_seq_len + gamma + 2, np.int32
                )
                base = req.prompt + req.out_tokens
                req.ctx[: len(base)] = base
                req.ctx_len = len(base)
                req.ngram_idx = {n: {} for n in (3, 2, 1)}
                req.ngram_upto = {n: 0 for n in (3, 2, 1)}
            elif req.ctx_len < need:
                fresh = req.out_tokens[req.ctx_len - len(req.prompt):]
                req.ctx[req.ctx_len:need] = fresh
                req.ctx_len = need
            out[slot] = self._ngram_propose_indexed(req, gamma)
        return out

    @staticmethod
    def _ngram_propose_indexed(req: _Request, gamma: int) -> np.ndarray:
        """O(γ)-per-step lookup: the last-occurrence index is extended
        only over the window starts added since the previous step."""
        ctx, L = req.ctx, req.ctx_len
        for n in (3, 2, 1):
            if L <= n:
                continue
            s = L - n  # the suffix's own start — never indexed
            idx = req.ngram_idx[n]
            for i in range(req.ngram_upto[n], s):
                idx[tuple(ctx[i : i + n].tolist())] = i
            req.ngram_upto[n] = s
            hit = idx.get(tuple(ctx[s:L].tolist()))
            if hit is not None:
                start = hit + n
                prop = ctx[start : min(start + gamma, L)]
                if len(prop):
                    pad = np.full(
                        gamma - len(prop), prop[-1], np.int32
                    )
                    return np.concatenate([prop, pad])
        return np.full(gamma, int(ctx[L - 1]), np.int32)

    @staticmethod
    def _ngram_propose(ctx: np.ndarray, gamma: int) -> np.ndarray:
        L = len(ctx)
        for n in (3, 2, 1):
            if L <= n:
                continue
            suffix = ctx[-n:]
            windows = np.lib.stride_tricks.sliding_window_view(ctx, n)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            hits = hits[hits < L - n]  # exclude the suffix itself
            if len(hits):
                start = int(hits[-1]) + n
                prop = ctx[start : start + gamma]
                if len(prop):
                    pad = np.full(gamma - len(prop), prop[-1], np.int32)
                    return np.concatenate([prop, pad])
        return np.full(gamma, int(ctx[-1]), np.int32)  # repeat-last fallback

    # ---- LoRA adapter admin (reference: internal/vllmclient/client.go) ------

    def loaded_adapters(self) -> list[str]:
        return sorted(self._adapter_slots)

    def load_adapter(self, name: str, adapter_weights: dict) -> None:
        """Install adapter weights into a free buffer slot. Weights:
        {target: (A [NL, in, r], B [NL, r, out])} with r <= max_lora_rank.
        Scaling (alpha/r) must already be folded into B."""
        if self._lora is None:
            raise ValueError("LoRA is disabled (max_adapters=0)")
        with self._lock:
            if name in self._adapter_slots:
                slot = self._adapter_slots[name]
                if self._adapter_in_use_locked(slot):
                    # Overwriting the slot would flip in-flight streams to
                    # the new weight version mid-generation — same hazard
                    # unload_adapter refuses.
                    raise RuntimeError(
                        f"adapter {name!r} has in-flight requests; retry "
                        "after they finish"
                    )
            else:
                if not self._adapter_free:
                    raise RuntimeError(
                        f"adapter capacity ({self.cfg.max_adapters}) exhausted"
                    )
                slot = self._adapter_free.pop(0)
            r_max = self.cfg.max_lora_rank
            for target, (A, B) in adapter_weights.items():
                if target not in self._lora:
                    raise KeyError(f"unknown LoRA target {target!r}")
                A = jnp.asarray(A)
                B = jnp.asarray(B)
                r = A.shape[-1]
                if r > r_max:
                    raise ValueError(
                        f"adapter rank {r} > max_lora_rank {r_max}"
                    )
                bufA = self._lora[target]["A"]
                bufB = self._lora[target]["B"]
                if r == r_max:
                    # Already slot-shaped (e.g. the lockstep broadcast
                    # payload pads to r_max before shipping).
                    padA = A.astype(bufA.dtype)
                    padB = B.astype(bufB.dtype)
                else:
                    padA = jnp.zeros(bufA.shape[1:], bufA.dtype).at[
                        ..., :r
                    ].set(A.astype(bufA.dtype))
                    padB = jnp.zeros(bufB.shape[1:], bufB.dtype).at[
                        :, :r, :
                    ].set(B.astype(bufB.dtype))
                self._lora[target]["A"] = bufA.at[slot].set(padA)
                self._lora[target]["B"] = bufB.at[slot].set(padB)
            self._adapter_slots[name] = slot
            # New weights in this slot index: prefix-cache entries hashed
            # under the old generation must never hit again.
            self._adapter_gen[slot] = self._adapter_gen.get(slot, 0) + 1

    def adapter_in_use(self, name: str) -> bool:
        """True when the adapter is loaded and any pending/active request
        references it. Advisory (state can change after return) — the
        load/unload guards re-check under the lock; callers use it to
        skip expensive work (e.g. weight downloads) that a 409 would
        discard."""
        if self._lora is None:
            return False
        with self._lock:
            slot = self._adapter_slots.get(name)
            return slot is not None and self._adapter_in_use_locked(slot)

    def _adapter_in_use_locked(self, slot: int) -> bool:
        """True when any pending/active request references the adapter
        slot. Caller holds self._lock (step() holds it for its whole
        body, so mid-admission requests can't be missed). Shared by the
        load/unload guards here and LockstepEngine's pre-broadcast
        mirror."""
        return any(
            r.adapter_idx == slot for r in self._sched.items()
        ) or any(r.adapter_idx == slot for r in self._active.values())

    def unload_adapter(self, name: str) -> bool:
        if self._lora is None or name not in self._adapter_slots:
            return False
        with self._lock:
            slot = self._adapter_slots.get(name)
            if slot is None:
                return False
            # Refuse while any request still decodes (or waits to decode)
            # with this adapter: zeroing the slot would silently flip the
            # stream to base-model output, and a subsequent load could
            # reassign the slot to a DIFFERENT adapter mid-stream.
            if self._adapter_in_use_locked(slot):
                raise RuntimeError(
                    f"adapter {name!r} has in-flight requests; retry after "
                    "they finish"
                )
            del self._adapter_slots[name]
            self._adapter_gen[slot] = self._adapter_gen.get(slot, 0) + 1
            for target in self._lora:
                bufA = self._lora[target]["A"]
                bufB = self._lora[target]["B"]
                self._lora[target]["A"] = bufA.at[slot].set(
                    jnp.zeros(bufA.shape[1:], bufA.dtype)
                )
                self._lora[target]["B"] = bufB.at[slot].set(
                    jnp.zeros(bufB.shape[1:], bufB.dtype)
                )
            self._adapter_free.append(slot)
            return True

    def generate(
        self,
        prompts: list[list[int]],
        params: SamplingParams | None = None,
        adapter: str | None = None,
    ) -> list[list[int]]:
        """Blocking batch generation (tests/benchmarks)."""
        rids = [self.add_request(p, params, adapter=adapter) for p in prompts]
        collected: dict[int, list[int]] = {r: [] for r in rids}
        while self.has_work():
            for ev in self.step():
                if ev.rid in collected:
                    collected[ev.rid].append(ev.token)
        return [collected[r] for r in rids]
