"""Continuous-batching inference engine core.

JetStream-style serving loop, in-process:

  add_request() ──► pending queue
                         │ (free slot?)
                 prefill (bucketed S, jitted) ─► insert KV into slot
                         │
        step(): one batched decode over ALL active slots (jitted, donated
                cache) ─► sample ─► host-side stop checks ─► free slots

TPU-first properties:
  - decode graph compiled ONCE (static [num_slots] batch); prefill compiled
    once per length bucket (powers of two) — bounded recompilation.
  - KV cache buffers are donated through the decode jit: no copy per step.
  - All device work is batched matmuls on the MXU; the host loop only does
    bookkeeping (slot free-lists, stop checks, detokenization upstream).

This engine is what the reference's `engine: VLLM` Pods provide externally
(reference: internal/modelcontroller/engine_vllm.go:12-167); here it is
in-tree and TPU-native. Its admin surface (LoRA load/unload) mirrors
reference: internal/vllmclient/client.go:30-73.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from kubeai_tpu.engine.kvcache import KVCache, insert_sequence
from kubeai_tpu.engine.sampling import SamplingParams, sample
from kubeai_tpu.models.registry import ModelFamily, get_model_family
from kubeai_tpu.parallel import sharding as psh
from kubeai_tpu.parallel.mesh import single_device_mesh


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    num_slots: int = 8
    max_seq_len: int = 1024
    prefill_buckets: tuple[int, ...] = ()  # default: powers of 2 up to max
    # Chunked prefill: prompts longer than this are prefilled in fixed
    # [1, prefill_chunk] steps against the slot cache — ONE compiled graph
    # for every prompt length and O(chunk * max_seq_len) activation memory
    # (0 = whole-prompt bucketed prefill only). Requires family support.
    prefill_chunk: int = 0
    cache_dtype: Any = jnp.bfloat16
    # Decode steps fused into one device call (lax.scan). Amortizes host
    # dispatch — critical when the chip sits behind an RPC tunnel. Tokens a
    # request emits past its stop point within a chunk are discarded
    # host-side; slot rows are independent, so batch-mates are unaffected.
    decode_chunk: int = 8
    # Weight-only quantization: "" (bf16) or "int8" (per-channel symmetric;
    # halves HBM weight traffic on the memory-bound decode path).
    quantization: str = ""
    # LoRA hot-swap: number of simultaneously loaded adapters (0 disables
    # the LoRA path entirely — no extra compute in the compiled graphs).
    max_adapters: int = 0
    max_lora_rank: int = 16
    # Pipelined stepping: dispatch decode chunk N+1 before fetching chunk
    # N's tokens, so the device computes through the host's fetch+process
    # time. Costs one chunk of extra stop-check latency. Default OFF: some
    # remote-dispatch transports (e.g. relayed single-chip tunnels) stall
    # with a second donated-buffer program in flight behind a pending
    # fetch; direct PJRT targets can enable it safely.
    pipeline: bool = False

    def buckets(self) -> tuple[int, ...]:
        if self.prefill_buckets:
            return self.prefill_buckets
        b, out = 16, []
        while b < self.max_seq_len:
            out.append(b)
            b *= 2
        out.append(self.max_seq_len)
        return tuple(out)


class StepEvent(NamedTuple):
    """One emitted token. `finish_reason` is "" while the request is live,
    else "stop" | "length" | "cancelled" (OpenAI finish_reason semantics)."""

    rid: int
    token: int
    finished: bool
    finish_reason: str = ""


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: list[int]
    params: SamplingParams
    seed: int
    adapter_idx: int = 0  # 0 = no adapter
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    position: int = 0  # absolute position of the next token to decode
    last_token: int = 0
    done: bool = False
    finish_reason: str = ""  # "stop" | "length" (OpenAI semantics)
    stop_token_ids: tuple[int, ...] = ()


class Engine:
    """Single-model, single-mesh continuous-batching engine."""

    def __init__(
        self,
        family: ModelFamily | str,
        model_cfg: Any,
        params: Any,
        mesh: Mesh | None = None,
        cfg: EngineConfig = EngineConfig(),
        rules: psh.ShardingRules = psh.DEFAULT_RULES,
        eos_token_ids: tuple[int, ...] = (),
    ):
        self.family = (
            get_model_family(family) if isinstance(family, str) else family
        )
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else single_device_mesh()
        self.rules = rules
        self.eos_token_ids = eos_token_ids
        self._lock = threading.Lock()
        self._next_rid = 0
        self._pending: deque[_Request] = deque()
        self._active: dict[int, _Request] = {}  # slot -> request
        self._requests: dict[int, _Request] = {}
        self._free_slots = list(range(cfg.num_slots))
        # In-flight decode chunk (pipelined stepping): (token futures,
        # snapshot of the slot->request map the chunk was dispatched with).
        self._inflight: tuple | None = None
        # Base entropy for unseeded requests (per-request seed = base ^ rid).
        self._seed_base = int.from_bytes(np.random.bytes(4), "little")
        self._steps = 0

        # Quantize (optional), then shard params onto the mesh.
        specs = self.family.param_specs(model_cfg)
        if cfg.quantization == "int8":
            from kubeai_tpu.engine.quantization import (
                quantize_params,
                quantized_specs,
            )

            params = quantize_params(params)
            specs = quantized_specs(specs, params["layers"])
        elif cfg.quantization:
            raise ValueError(f"unknown quantization {cfg.quantization!r}")
        self.params = psh.shard_params(params, specs, self.mesh, rules)

        # GQA: when tp exceeds the KV-head count the cache can't shard on
        # heads — replicate it across tp (each shard attends with its local
        # q heads against the full KV; standard GQA-on-TPU fallback).
        cache_rules = rules
        tp_size = self.mesh.shape.get("tp", 1)
        if model_cfg.num_kv_heads % max(tp_size, 1) != 0:
            cache_rules = psh.ShardingRules(
                rules=tuple(
                    (name, None if name == psh.KV_HEADS else phys)
                    for name, phys in rules.rules
                )
            )
        cache_sharding = psh.named_sharding(
            self.mesh, KVCache.logical_axes(), cache_rules
        )
        self.cache = KVCache.create(
            model_cfg.num_layers,
            cfg.num_slots,
            cfg.max_seq_len,
            model_cfg.num_kv_heads,
            model_cfg.head_size,
            dtype=cfg.cache_dtype,
            sharding=cache_sharding,
        )

        # Per-slot decode state lives ON DEVICE (replicated): steady-state
        # decode then needs ZERO host->device transfers per chunk — critical
        # when each transfer costs a network round trip to the chip.
        B = cfg.num_slots
        self._state = {
            "tokens": jnp.zeros((B,), jnp.int32),
            "positions": jnp.zeros((B,), jnp.int32),
            "seeds": jnp.zeros((B,), jnp.uint32),
            "temp": jnp.zeros((B,), jnp.float32),
            "topk": jnp.zeros((B,), jnp.int32),
            "topp": jnp.ones((B,), jnp.float32),
            "lora_idx": jnp.zeros((B,), jnp.int32),
        }

        # LoRA adapter buffers: fixed shapes, slot 0 = zeros ("no adapter").
        # Loading an adapter updates a buffer slice — never a recompile.
        self._lora = None
        self._adapter_slots: dict[str, int] = {}
        if cfg.max_adapters > 0:
            if not hasattr(self.family, "init_lora_buffers"):
                from kubeai_tpu.models import llama as _llama

                init_fn = _llama.init_lora_buffers
            else:
                init_fn = self.family.init_lora_buffers
            self._lora = init_fn(
                model_cfg, cfg.max_adapters + 1, cfg.max_lora_rank
            )
            self._adapter_free = list(range(1, cfg.max_adapters + 1))

        self._build_jits(cache_sharding)

    # ---- compiled functions -------------------------------------------------

    def _build_jits(self, cache_sharding) -> None:
        fam, mcfg = self.family, self.model_cfg
        max_len = self.cfg.max_seq_len
        chunk = max(1, self.cfg.decode_chunk)

        def _prefill_admit(params, tokens, ints, floats, ck, cv, state, lora):
            """Fused prefill → cache insert → first-token sample → slot-state
            update: ONE device call per admitted request. `ints` packs
            [length, slot, seed, top_k, adapter]; `floats` packs
            [temp, top_p] — two small transfers instead of seven."""
            length, slot, seed, topk = ints[0], ints[1], ints[2], ints[3]
            adapter = ints[4]
            temp, topp = floats[0], floats[1]
            if lora is None:
                logits, k_all, v_all = fam.prefill(
                    params, mcfg, tokens, length[None]
                )
            else:
                logits, k_all, v_all = fam.prefill(
                    params, mcfg, tokens, length[None],
                    lora=lora, lora_idx=adapter[None],
                )
            ck, cv = insert_sequence(ck, cv, k_all[:, 0], v_all[:, 0], slot)
            tok = sample(
                logits,
                seed.astype(jnp.uint32)[None],
                length[None],
                temp[None],
                topk[None],
                topp[None],
            )[0]
            state = dict(
                tokens=state["tokens"].at[slot].set(tok),
                positions=state["positions"].at[slot].set(length),
                seeds=state["seeds"].at[slot].set(seed.astype(jnp.uint32)),
                temp=state["temp"].at[slot].set(temp),
                topk=state["topk"].at[slot].set(topk),
                topp=state["topp"].at[slot].set(topp),
                lora_idx=state["lora_idx"].at[slot].set(adapter),
            )
            return tok, ck, cv, state

        self._prefill_admit_jit = jax.jit(
            _prefill_admit,
            donate_argnums=(4, 5, 6),
            out_shardings=(None, cache_sharding, cache_sharding, None),
            static_argnames=(),
        )

        def _decode_chunk(params, ck, cv, state, lora):
            """`chunk` decode steps fused via lax.scan; emits [chunk, B]
            tokens per device call. No host inputs besides the (donated,
            device-resident) cache and slot state. Write positions are
            clamped so rows that pass their stop point within a chunk stay
            in-bounds (their surplus tokens are discarded host-side)."""
            seeds, temp = state["seeds"], state["temp"]
            topk, topp = state["topk"], state["topp"]

            def body(carry, _):
                tokens, positions, ck, cv = carry
                if lora is None:
                    logits, ck, cv = fam.decode_step(
                        params, mcfg, tokens, positions, ck, cv
                    )
                else:
                    logits, ck, cv = fam.decode_step(
                        params, mcfg, tokens, positions, ck, cv,
                        lora=lora, lora_idx=state["lora_idx"],
                    )
                # Sampled token lands at position+1 — the fold-in value, so
                # a seeded request replays identically across batches.
                toks = sample(logits, seeds, positions + 1, temp, topk, topp)
                next_pos = jnp.minimum(positions + 1, max_len - 1)
                return (toks, next_pos, ck, cv), toks

            (tokens, positions, ck, cv), toks_seq = jax.lax.scan(
                body,
                (state["tokens"], state["positions"], ck, cv),
                None,
                length=chunk,
            )
            state = dict(state, tokens=tokens, positions=positions)
            return toks_seq, ck, cv, state

        self._decode_jit = jax.jit(
            _decode_chunk,
            donate_argnums=(1, 2, 3),
            out_shardings=(None, cache_sharding, cache_sharding, None),
        )

        if self.cfg.prefill_chunk > 0:
            if not hasattr(fam, "prefill_chunk") and fam.name != "llama":
                raise ValueError(
                    f"family {fam.name} does not support chunked prefill"
                )
            from kubeai_tpu.models import llama as _llama

            chunk_fn = getattr(fam, "prefill_chunk", None) or _llama.prefill_chunk

            def _slot_slice(c, slot):
                nl, _, L, kvh, d = c.shape
                sl = jax.lax.dynamic_slice(
                    c, (0, slot, 0, 0, 0), (nl, 1, L, kvh, d)
                )
                return sl[:, 0]

            def _slot_write(c, slot, sl):
                return jax.lax.dynamic_update_slice(
                    c, sl[:, None].astype(c.dtype), (0, slot, 0, 0, 0)
                )

            def _chunk_mid(params, tokens, ints, ck, cv, lora):
                start, slot, length, adapter = ints[0], ints[1], ints[2], ints[3]
                ks, vs = _slot_slice(ck, slot), _slot_slice(cv, slot)
                _, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=False,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                return _slot_write(ck, slot, ks), _slot_write(cv, slot, vs)

            self._prefill_chunk_mid_jit = jax.jit(
                _chunk_mid,
                donate_argnums=(3, 4),
                static_argnums=(),
                out_shardings=(cache_sharding, cache_sharding),
            )

            def _chunk_last(params, tokens, ints, floats, ck, cv, state, lora):
                start, slot, length = ints[0], ints[1], ints[2]
                adapter, seed, topk = ints[3], ints[4], ints[5]
                temp, topp = floats[0], floats[1]
                ks, vs = _slot_slice(ck, slot), _slot_slice(cv, slot)
                logits, ks, vs = chunk_fn(
                    params, mcfg, tokens, start, length, ks, vs,
                    want_logits=True,
                    lora=lora,
                    lora_idx=None if lora is None else adapter[None],
                )
                ck = _slot_write(ck, slot, ks)
                cv = _slot_write(cv, slot, vs)
                tok = sample(
                    logits,
                    seed.astype(jnp.uint32)[None],
                    length[None],
                    temp[None],
                    topk[None],
                    topp[None],
                )[0]
                state = dict(
                    tokens=state["tokens"].at[slot].set(tok),
                    positions=state["positions"].at[slot].set(length),
                    seeds=state["seeds"].at[slot].set(seed.astype(jnp.uint32)),
                    temp=state["temp"].at[slot].set(temp),
                    topk=state["topk"].at[slot].set(topk),
                    topp=state["topp"].at[slot].set(topp),
                    lora_idx=state["lora_idx"].at[slot].set(adapter),
                )
                return tok, ck, cv, state

            self._prefill_chunk_last_jit = jax.jit(
                _chunk_last,
                donate_argnums=(4, 5, 6),
                out_shardings=(None, cache_sharding, cache_sharding, None),
            )

    # ---- public API ---------------------------------------------------------

    def add_request(
        self,
        prompt_tokens: list[int],
        params: SamplingParams | None = None,
        adapter: str | None = None,
        on_admit=None,
    ) -> int:
        """Queue a request. `on_admit(rid)` runs under the engine lock
        before the request becomes visible to `step()` — callers use it to
        register event subscribers without racing a concurrent serve loop
        (a request admitted and finished before registration would
        otherwise drop its events)."""
        params = params or SamplingParams()
        adapter_idx = 0
        if adapter:
            if self._lora is None:
                raise ValueError("LoRA is disabled (max_adapters=0)")
            if adapter not in self._adapter_slots:
                raise KeyError(f"adapter {adapter!r} not loaded")
            adapter_idx = self._adapter_slots[adapter]
        if len(prompt_tokens) == 0:
            raise ValueError("empty prompt")
        if len(prompt_tokens) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt_tokens)} >= max_seq_len {self.cfg.max_seq_len}"
            )
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            seed = (
                params.seed
                if params.seed is not None
                else (self._seed_base ^ rid)
            ) & 0xFFFFFFFF
            req = _Request(
                rid=rid,
                prompt=list(prompt_tokens),
                params=params,
                seed=seed,
                adapter_idx=adapter_idx,
                stop_token_ids=self.eos_token_ids,
            )
            self._requests[rid] = req
            if on_admit is not None:
                try:
                    on_admit(rid)
                except BaseException:
                    del self._requests[rid]
                    raise
            self._pending.append(req)
            return rid

    def has_work(self) -> bool:
        return bool(self._pending or self._active or self._inflight)

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def num_pending(self) -> int:
        return len(self._pending)

    def _bucket(self, n: int) -> int:
        for b in self.cfg.buckets():
            if n <= b:
                return b
        return self.cfg.max_seq_len

    def _admit_pending(self) -> list[StepEvent]:
        """Prefill pending requests into free slots. Returns emitted tokens."""
        emitted = []
        while self._pending and self._free_slots:
            req = self._pending.popleft()
            slot = self._free_slots.pop()
            req.slot = slot
            plen = len(req.prompt)
            C = self.cfg.prefill_chunk
            if C > 0 and plen > C:
                tok = self._admit_chunked(req, slot, plen, C)
                emitted.append(self._finish_admission(req, slot, plen, tok))
                continue
            bucket = self._bucket(plen)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :plen] = req.prompt
            tok_dev, self.cache.k, self.cache.v, self._state = (
                self._prefill_admit_jit(
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(
                        [
                            plen,
                            slot,
                            # uint32 seed bit-cast into the int32 pack; the
                            # jit reinterprets it back via astype(uint32).
                            int(np.uint32(req.seed).view(np.int32)),
                            req.params.top_k,
                            req.adapter_idx,
                        ],
                        jnp.int32,
                    ),
                    jnp.asarray(
                        [req.params.temperature, req.params.top_p], jnp.float32
                    ),
                    self.cache.k,
                    self.cache.v,
                    self._state,
                    self._lora,
                )
            )
            emitted.append(
                self._finish_admission(req, slot, plen, int(tok_dev))
            )
        return emitted

    def _finish_admission(
        self, req: _Request, slot: int, plen: int, tok: int
    ) -> StepEvent:
        req.out_tokens.append(tok)
        req.position = plen
        req.last_token = tok
        finished = self._check_stop(req)
        if finished:
            self._release(req)
        else:
            self._active[slot] = req
        return StepEvent(req.rid, tok, finished, req.finish_reason)

    def _admit_chunked(self, req: _Request, slot: int, plen: int, C: int) -> int:
        """Prefill a long prompt chunk-by-chunk into the slot cache; the
        final chunk also samples the first token and updates slot state."""
        n_chunks = -(-plen // C)
        padded = np.zeros((1, n_chunks * C), np.int32)
        padded[0, :plen] = req.prompt
        for i in range(n_chunks - 1):
            self.cache.k, self.cache.v = self._prefill_chunk_mid_jit(
                self.params,
                jnp.asarray(padded[:, i * C : (i + 1) * C]),
                jnp.asarray(
                    [i * C, slot, plen, req.adapter_idx], jnp.int32
                ),
                self.cache.k,
                self.cache.v,
                self._lora,
            )
        last = n_chunks - 1
        tok_dev, self.cache.k, self.cache.v, self._state = (
            self._prefill_chunk_last_jit(
                self.params,
                jnp.asarray(padded[:, last * C :]),
                jnp.asarray(
                    [
                        last * C,
                        slot,
                        plen,
                        req.adapter_idx,
                        int(np.uint32(req.seed).view(np.int32)),
                        req.params.top_k,
                    ],
                    jnp.int32,
                ),
                jnp.asarray(
                    [req.params.temperature, req.params.top_p], jnp.float32
                ),
                self.cache.k,
                self.cache.v,
                self._state,
                self._lora,
            )
        )
        return int(tok_dev)

    def _check_stop(self, req: _Request) -> bool:
        if req.last_token in req.stop_token_ids:
            req.done = True
            req.finish_reason = "stop"
        elif len(req.out_tokens) >= req.params.max_tokens:
            req.done = True
            req.finish_reason = "length"
        elif req.position >= self.cfg.max_seq_len:
            # Next decode would write past the cache; the token just emitted
            # needed no cache slot, so capacity is fully used.
            req.done = True
            req.finish_reason = "length"
        return req.done

    def _release(self, req: _Request) -> None:
        if req.slot >= 0:
            self._active.pop(req.slot, None)
            self._free_slots.append(req.slot)
            req.slot = -1
        # Finished/cancelled requests leave the table immediately: callers
        # consume tokens from step() events, so retaining them would leak
        # (one _Request per request for the process lifetime).
        self._requests.pop(req.rid, None)

    def cancel(self, rid: int) -> bool:
        """Abort a request (pending or active). Safe mid-stream: the slot's
        stale KV is masked by per-slot lengths when the slot is reused."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return False
            if req in self._pending:
                self._pending.remove(req)
            req.done = True
            req.finish_reason = "cancelled"
            self._release(req)
            return True

    def step(self) -> list[StepEvent]:
        """Admit pending prefills, then run one fused decode chunk
        (cfg.decode_chunk model steps in a single device call).

        With cfg.pipeline, the chunk dispatched this call is fetched on the
        NEXT call: the device computes chunk N+1 while the host fetches and
        processes chunk N's tokens.

        Returns a list of StepEvents in emission order.
        """
        with self._lock:
            emitted = self._admit_pending()
            prev = self._inflight
            self._inflight = None
            current = None
            if self._active:
                toks_seq, self.cache.k, self.cache.v, self._state = (
                    self._decode_jit(
                        self.params, self.cache.k, self.cache.v, self._state,
                        self._lora,
                    )
                )
                self._steps += 1
                current = (toks_seq, list(self._active.items()))
                if self.cfg.pipeline:
                    # Fetch current NEXT call: device computes through the
                    # host's fetch+process of prev.
                    self._inflight = current
                    current = None
            if prev is not None:
                emitted.extend(self._process_chunk(prev))
            if current is not None:
                emitted.extend(self._process_chunk(current))
            return emitted

    def _process_chunk(self, inflight: tuple) -> list[StepEvent]:
        toks_seq, chunk_slots = inflight
        toks_seq = np.asarray(jax.device_get(toks_seq))  # [chunk, B]
        emitted: list[StepEvent] = []
        for k in range(toks_seq.shape[0]):
            for slot, req in chunk_slots:
                if req.done:
                    continue  # surplus chunk tokens discarded
                tok = int(toks_seq[k, slot])
                req.out_tokens.append(tok)
                req.position += 1
                req.last_token = tok
                finished = self._check_stop(req)
                emitted.append(
                    StepEvent(req.rid, tok, finished, req.finish_reason)
                )
                if finished:
                    self._release(req)
        return emitted

    # ---- LoRA adapter admin (reference: internal/vllmclient/client.go) ------

    def loaded_adapters(self) -> list[str]:
        return sorted(self._adapter_slots)

    def load_adapter(self, name: str, adapter_weights: dict) -> None:
        """Install adapter weights into a free buffer slot. Weights:
        {target: (A [NL, in, r], B [NL, r, out])} with r <= max_lora_rank.
        Scaling (alpha/r) must already be folded into B."""
        if self._lora is None:
            raise ValueError("LoRA is disabled (max_adapters=0)")
        with self._lock:
            if name in self._adapter_slots:
                slot = self._adapter_slots[name]
            else:
                if not self._adapter_free:
                    raise RuntimeError(
                        f"adapter capacity ({self.cfg.max_adapters}) exhausted"
                    )
                slot = self._adapter_free.pop(0)
            r_max = self.cfg.max_lora_rank
            for target, (A, B) in adapter_weights.items():
                if target not in self._lora:
                    raise KeyError(f"unknown LoRA target {target!r}")
                A = jnp.asarray(A)
                B = jnp.asarray(B)
                r = A.shape[-1]
                if r > r_max:
                    raise ValueError(
                        f"adapter rank {r} > max_lora_rank {r_max}"
                    )
                bufA = self._lora[target]["A"]
                bufB = self._lora[target]["B"]
                padA = jnp.zeros(bufA.shape[1:], bufA.dtype).at[
                    ..., :r
                ].set(A.astype(bufA.dtype))
                padB = jnp.zeros(bufB.shape[1:], bufB.dtype).at[
                    :, :r, :
                ].set(B.astype(bufB.dtype))
                self._lora[target]["A"] = bufA.at[slot].set(padA)
                self._lora[target]["B"] = bufB.at[slot].set(padB)
            self._adapter_slots[name] = slot

    def unload_adapter(self, name: str) -> bool:
        if self._lora is None or name not in self._adapter_slots:
            return False
        with self._lock:
            slot = self._adapter_slots.pop(name)
            for target in self._lora:
                bufA = self._lora[target]["A"]
                bufB = self._lora[target]["B"]
                self._lora[target]["A"] = bufA.at[slot].set(
                    jnp.zeros(bufA.shape[1:], bufA.dtype)
                )
                self._lora[target]["B"] = bufB.at[slot].set(
                    jnp.zeros(bufB.shape[1:], bufB.dtype)
                )
            self._adapter_free.append(slot)
            return True

    def generate(
        self,
        prompts: list[list[int]],
        params: SamplingParams | None = None,
        adapter: str | None = None,
    ) -> list[list[int]]:
        """Blocking batch generation (tests/benchmarks)."""
        rids = [self.add_request(p, params, adapter=adapter) for p in prompts]
        collected: dict[int, list[int]] = {r: [] for r in rids}
        while self.has_work():
            for ev in self.step():
                if ev.rid in collected:
                    collected[ev.rid].append(ev.token)
        return [collected[r] for r in rids]
