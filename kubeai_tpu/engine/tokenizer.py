"""Tokenizer seam: HuggingFace tokenizers in production, a byte-level
fallback for offline tests.

Chat templating follows the tokenizer's own template when present
(`apply_chat_template`), else a minimal generic template — the engine
serves /v1/chat/completions either way.
"""

from __future__ import annotations

import os
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    eos_token_ids: tuple[int, ...]

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict]) -> list[int]: ...


class ByteTokenizer:
    """Offline fallback: UTF-8 bytes + 0 as BOS/EOS. Vocab 257."""

    vocab_size = 257
    eos_token_ids = (256,)

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace"
        )

    def apply_chat_template(self, messages: list[dict]) -> list[int]:
        text = _generic_chat_text(messages)
        return self.encode(text)


class HFTokenizer:
    def __init__(self, model_dir: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(model_dir)
        eos = self._tok.eos_token_id
        ids = []
        if eos is not None:
            ids.append(int(eos))
        # Llama-3 end-of-turn token also terminates generation.
        for special in ("<|eot_id|>", "<|im_end|>", "<|end|>"):
            try:
                tid = self._tok.convert_tokens_to_ids(special)
                if tid is not None and tid >= 0 and tid not in ids:
                    ids.append(int(tid))
            except Exception:
                pass
        self.eos_token_ids = tuple(ids)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> list[int]:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, add_generation_prompt=True
            )
        return self.encode(_generic_chat_text(messages))


def _generic_chat_text(messages: list[dict]) -> str:
    parts = []
    for m in messages:
        content = m.get("content", "")
        if isinstance(content, list):
            content = " ".join(
                p.get("text", "") for p in content
                if isinstance(p, dict) and p.get("type") == "text"
            )
        parts.append(f"{m.get('role', 'user')}: {content}")
    parts.append("assistant:")
    return "\n".join(parts)


def load_tokenizer(model_dir: str | None) -> Tokenizer:
    if model_dir and os.path.isdir(model_dir):
        try:
            return HFTokenizer(model_dir)
        except Exception:
            pass
    return ByteTokenizer()
