"""Paged KV cache: block-table paging over a shared page pool.

The slot cache (kvcache.py) preallocates [slots, max_seq_len] per slot —
simple and fast, but HBM scales with the worst case. Paging allocates
fixed-size pages on demand from a shared pool, so memory scales with the
TOKENS ACTUALLY RESIDENT, buying more concurrent slots per chip under
mixed-length traffic (the vLLM insight, rebuilt TPU-style: static
shapes — the pool and block tables are fixed-size buffers; only their
CONTENTS change).

Layout:
  k_pages / v_pages: [NL, n_pages, page_size, KVH, D]
  block_tables:      [slots, max_pages_per_slot] int32 (page ids; -1 free)
  host allocator:    free-list of page ids (bookkeeping outside jit)

Ops (jit-safe, tested against contiguous semantics):
  gather_slot_kv     — virtual [slots, L] view for decode attention
  scatter_token      — write one token's K/V per slot through the tables
  insert_sequence    — write a prefilled sequence through the tables

Engine integration (cache_mode="paged" + a Pallas ragged-paged-attention
decode kernel that reads pages in place instead of gathering) is the
round-2 item tracked in ROADMAP.md; this module is the validated
bookkeeping + functional reference it drops into.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


class OutOfPages(RuntimeError):
    pass


@dataclasses.dataclass
class PagedKVCache:
    # A pool is either a plain [NL, n_pages, page, KVH, D] array or an
    # int8-quantized {"q8": int8 pages, "scale": f32 [NL, n_pages, page,
    # KVH]} dict (ops/kv_quant.py) — the same leaf-dispatch idiom the
    # weight quantizer uses, so jit plumbing and layer scans carry both
    # unchanged.
    k_pages: jax.Array | dict  # [NL, n_pages, page, KVH, D]
    v_pages: jax.Array | dict
    block_tables: jax.Array  # [slots, max_pages] int32, -1 = unallocated

    @property
    def quantized(self) -> bool:
        from kubeai_tpu.ops.kv_quant import is_quantized_kv

        return is_quantized_kv(self.k_pages)

    @property
    def pages_shape(self) -> tuple:
        from kubeai_tpu.ops.kv_quant import kv_pages_shape

        return kv_pages_shape(self.k_pages)

    @property
    def page_size(self) -> int:
        return self.pages_shape[2]

    @property
    def num_pages(self) -> int:
        return self.pages_shape[1]

    @property
    def max_pages_per_slot(self) -> int:
        return self.block_tables.shape[1]

    def nbytes(self) -> int:
        """Resident pool bytes (pages + scales when quantized)."""
        from kubeai_tpu.ops.kv_quant import kv_pool_nbytes

        return kv_pool_nbytes(self.k_pages) + kv_pool_nbytes(self.v_pages)

    @staticmethod
    def create(
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_slots: int,
        max_seq_len: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        from kubeai_tpu.ops.kv_quant import make_quantized_pool

        max_pages = -(-max_seq_len // page_size)
        shape = (num_layers, num_pages, page_size, kv_heads, head_dim)
        if dtype in (jnp.int8, "int8"):
            k_pages = make_quantized_pool(shape)
            v_pages = make_quantized_pool(shape)
        else:
            k_pages = jnp.zeros(shape, dtype)
            v_pages = jnp.zeros(shape, dtype)
        return PagedKVCache(
            k_pages=k_pages,
            v_pages=v_pages,
            block_tables=jnp.full((num_slots, max_pages), -1, jnp.int32),
        )


jax.tree_util.register_dataclass(
    PagedKVCache, ["k_pages", "v_pages", "block_tables"], []
)


class SequenceTooLong(RuntimeError):
    pass


class PageAllocator:
    """Host-side free-list with optional prefix-cache sharing. The device
    never sees allocation — only the resulting block tables.

    Page 0 is RESERVED as a scratch page and never handed out: jit-safe
    ops clamp unallocated block-table entries (-1) to 0, so reads hit
    masked junk and writes land in scratch — never in a live sequence.

    Prefix caching (the vLLM automatic-prefix-cache idea, host-side
    bookkeeping only): immutable full-page prompt prefixes register under
    a content-hash chain. A later prompt whose leading pages hash to a
    registered chain ADOPTS those pages read-only instead of recomputing
    them — pages then carry a slot refcount, and pages whose refcount
    drops to zero park in an LRU idle pool (still lookupable) that the
    free path evicts from only when the free list runs dry. The reference
    exploits engine prefix caches only ACROSS replicas (CHWBL routing,
    docs/benchmarks/prefix-aware-load-balancing.md); this gives the
    in-tree engine the per-replica half of that headline."""

    def __init__(
        self, num_pages: int, page_size: int,
        max_pages_per_slot: int | None = None,
    ):
        from collections import OrderedDict

        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        self._free = list(range(1, num_pages))  # page 0 reserved
        # slot -> allocated page ids, in order.
        self._owned: dict[int, list[int]] = {}
        # Prefix-cache state. A page is in exactly one of: _free, owned
        # (refcount >= 1), or _idle (refcount 0 but still registered).
        self._ref: dict[int, int] = {}
        self._hash_to_page: dict[bytes, int] = {}
        self._page_to_hash: dict[int, bytes] = {}
        self._idle: "OrderedDict[int, None]" = OrderedDict()  # LRU -> MRU
        # Optional spill hook: called as on_evict(page, hash) just before
        # an idle page's registration is destroyed by eviction, while the
        # device page still holds the registered content. Wired by the
        # engine when KV objstore spill is enabled; must never raise.
        self.on_evict = None

    @property
    def free_pages(self) -> int:
        """Pages an ensure() can still obtain (idle cached pages are
        reclaimable by eviction)."""
        return len(self._free) + len(self._idle)

    @property
    def cached_idle_pages(self) -> int:
        return len(self._idle)

    def pages_for(self, slot: int) -> list[int]:
        return list(self._owned.get(slot, []))

    def _take_free(self) -> int | None:
        if self._free:
            return self._free.pop()
        if self._idle:
            # Eviction MUST strip both hash mappings atomically with the
            # idle-pool removal: once holdings are published cluster-wide
            # a stale _hash_to_page entry would let lookup() adopt a page
            # whose content has been overwritten by its new owner —
            # silently corrupting token-identity. Regression-tested in
            # tests/unit/test_paged_cache.py.
            page, _ = self._idle.popitem(last=False)  # evict LRU
            h = self._page_to_hash.pop(page)
            del self._hash_to_page[h]
            if self.on_evict is not None:
                try:
                    self.on_evict(page, h)
                except Exception:
                    pass
            del self._ref[page]
            return page
        return None

    def ensure(self, slot: int, length: int) -> list[int]:
        """Grow slot's allocation to cover `length` tokens. Returns the page
        list. Raises OutOfPages when the pool is exhausted (pages taken in
        the failed call are rolled back, so a deferred admission holds
        nothing) and SequenceTooLong past the per-slot block-table cap."""
        need = -(-length // self.page_size)
        if self.max_pages_per_slot is not None and need > self.max_pages_per_slot:
            raise SequenceTooLong(
                f"{length} tokens need {need} pages > per-slot cap "
                f"{self.max_pages_per_slot}"
            )
        owned = self._owned.setdefault(slot, [])
        # Capacity check BEFORE touching the idle cache: _take_free
        # destroys an evicted page's hash entries, so an allocation that
        # cannot succeed must not strip the cache on its way to the
        # OutOfPages it was always going to raise.
        if need - len(owned) > len(self._free) + len(self._idle):
            raise OutOfPages(
                f"page pool exhausted ({need} needed for slot {slot})"
            )
        taken: list[int] = []
        while len(owned) + len(taken) < need:
            page = self._take_free()
            if page is None:  # unreachable given the check above
                self._free.extend(taken)
                raise OutOfPages(
                    f"page pool exhausted ({need} needed for slot {slot})"
                )
            taken.append(page)
        for page in taken:
            self._ref[page] = 1
        owned.extend(taken)
        return list(owned)

    def _decref(self, page: int) -> None:
        n = self._ref.get(page, 1) - 1
        if n > 0:
            self._ref[page] = n
        elif page in self._page_to_hash:
            # Still registered: park in the idle LRU, content intact.
            self._ref[page] = 0
            self._idle[page] = None
        else:
            self._ref.pop(page, None)
            self._free.append(page)

    def release(self, slot: int) -> None:
        for page in self._owned.pop(slot, []):
            self._decref(page)

    # ---- prefix cache ------------------------------------------------------

    def lookup(self, hashes: list[bytes]) -> list[int]:
        """Longest registered prefix of the hash chain -> its pages, in
        order. Hit pages are NOT reserved — call adopt() to take refs."""
        pages: list[int] = []
        for h in hashes:
            page = self._hash_to_page.get(h)
            if page is None:
                break
            pages.append(page)
        return pages

    def adopt(self, slot: int, pages: list[int]) -> None:
        """Prepend shared pages to slot's allocation (before any ensure()
        growth), taking a reference on each; idle pages come off the LRU."""
        owned = self._owned.setdefault(slot, [])
        assert not owned, "adopt() must seed an empty slot"
        for page in pages:
            self._ref[page] = self._ref.get(page, 0) + 1
            self._idle.pop(page, None)
        owned.extend(pages)

    def unadopt(self, slot: int) -> None:
        """Roll back an adopt() whose follow-up ensure() failed."""
        for page in self._owned.pop(slot, []):
            self._decref(page)

    def register(self, hashes: list[bytes], pages: list[int]) -> None:
        """Publish a slot's immutable full prompt pages under their chain
        hashes. First registration of a hash wins (concurrent identical
        prompts produce identical content anyway); a page already
        registered under another hash keeps its original entry."""
        for h, page in zip(hashes, pages):
            if h in self._hash_to_page or page in self._page_to_hash:
                continue
            self._hash_to_page[h] = page
            self._page_to_hash[page] = h

    def holdings(self) -> list[bytes]:
        """Every chain hash currently registered (owned-and-registered or
        parked idle) — the replica's advertisable prefix-cache contents.
        Advisory only: routing built on this is a hint; admission always
        re-verifies through lookup(), so staleness can cost performance
        but never correctness."""
        return list(self._hash_to_page.keys())

    def seed_unowned(self, hashes: list[bytes]) -> list[int] | None:
        """Allocate pages for externally fetched prefix content (peer KV
        fetch / objstore fill): one page per NOVEL hash, registered and
        parked straight into the idle LRU with refcount 0 — no slot owns
        them; the next admission adopts them through the ordinary
        lookup()/adopt() path. Returns the page ids aligned with `hashes`
        (None entries mark hashes that were already registered locally and
        need no write), or None if the pool cannot supply every novel page
        (partial seeding is rolled back so a failed fetch holds nothing).
        """
        # Novelty is decided ONCE, before any page is taken: taking pages
        # can evict idle entries, which may deregister a hash classified
        # as already-held — it must still consume no page (its chain link
        # just breaks, shortening future lookups; never a correctness
        # issue because admission re-verifies content by hash).
        novel = {h for h in hashes if h not in self._hash_to_page}
        taken: list[int] = []
        for _ in range(len(novel)):
            page = self._take_free()
            if page is None:
                self._free.extend(taken)
                return None
            taken.append(page)
        it = iter(taken)
        out: list[int | None] = []
        for h in hashes:
            if h not in novel:
                out.append(None)
                continue
            page = next(it)
            self._hash_to_page[h] = page
            self._page_to_hash[page] = h
            self._ref[page] = 0
            self._idle[page] = None
            out.append(page)
        return out



def set_block_table(
    block_tables: jax.Array, slot: int, pages: list[int]
) -> jax.Array:
    row = jnp.full((block_tables.shape[1],), -1, jnp.int32)
    if pages:
        row = row.at[: len(pages)].set(jnp.asarray(pages, jnp.int32))
    return block_tables.at[slot].set(row)


def gather_slot_kv(cache: PagedKVCache) -> tuple[jax.Array, jax.Array]:
    """Materialize the virtual contiguous view [NL, slots, L_max, KVH, D].

    L_max = max_pages_per_slot * page_size. Unallocated pages (-1) index
    page 0 — garbage that decode attention masks via per-slot lengths.
    This is the functional reference; the paged-attention kernel reads
    pages in place and never materializes this view.
    """
    from kubeai_tpu.ops.kv_quant import dequantize_kv

    bt = jnp.maximum(cache.block_tables, 0)  # -1 -> reserved scratch page 0
    if cache.quantized:
        k = dequantize_kv(
            cache.k_pages["q8"][:, bt], cache.k_pages["scale"][:, bt]
        )
        v = dequantize_kv(
            cache.v_pages["q8"][:, bt], cache.v_pages["scale"][:, bt]
        )
    else:
        k = cache.k_pages[:, bt]  # [NL, slots, max_pages, page, KVH, D]
        v = cache.v_pages[:, bt]
    nl, slots, mp, page, kvh, d = k.shape
    return (
        k.reshape(nl, slots, mp * page, kvh, d),
        v.reshape(nl, slots, mp * page, kvh, d),
    )


def scatter_token(
    cache: PagedKVCache,
    k_new: jax.Array,  # [NL, slots, KVH, D] one token per slot
    v_new: jax.Array,
    positions: jax.Array,  # [slots] absolute position of the token
) -> PagedKVCache:
    """Write one token per slot through the block tables (decode step)."""
    from kubeai_tpu.ops.kv_quant import quantize_kv

    page = cache.page_size
    slot_idx = jnp.arange(cache.block_tables.shape[0])
    page_ids = cache.block_tables[slot_idx, positions // page]  # [slots]
    # Unallocated slots (-1) write into the RESERVED scratch page 0 — safe
    # because the allocator never hands page 0 to a live sequence.
    page_ids = jnp.maximum(page_ids, 0)
    offsets = positions % page
    if cache.quantized:
        k8, ks = quantize_kv(k_new)
        v8, vs = quantize_kv(v_new)
        k_pages = {
            "q8": cache.k_pages["q8"].at[:, page_ids, offsets].set(k8),
            "scale": cache.k_pages["scale"].at[:, page_ids, offsets].set(ks),
        }
        v_pages = {
            "q8": cache.v_pages["q8"].at[:, page_ids, offsets].set(v8),
            "scale": cache.v_pages["scale"].at[:, page_ids, offsets].set(vs),
        }
        return PagedKVCache(k_pages, v_pages, cache.block_tables)
    k_pages = cache.k_pages.at[:, page_ids, offsets].set(
        k_new.astype(cache.k_pages.dtype)
    )
    v_pages = cache.v_pages.at[:, page_ids, offsets].set(
        v_new.astype(cache.v_pages.dtype)
    )
    return PagedKVCache(k_pages, v_pages, cache.block_tables)


def insert_sequence(
    cache: PagedKVCache,
    k_seq: jax.Array,  # [NL, S, KVH, D] prefilled sequence (padded)
    v_seq: jax.Array,
    slot: int,
    length: int,
) -> PagedKVCache:
    """Write a prefilled sequence through slot's block table (admission)."""
    from kubeai_tpu.ops.kv_quant import quantize_kv

    page = cache.page_size
    bt = cache.block_tables
    k_pages, v_pages = cache.k_pages, cache.v_pages
    n_pages = -(-length // page)
    for p in range(n_pages):
        pid = bt[slot, p]
        pid = jnp.maximum(pid, 0)
        start = p * page
        count = min(page, length - start)
        ks = k_seq[:, start : start + count]
        vs = v_seq[:, start : start + count]
        if cache.quantized:
            k8, ksc = quantize_kv(ks)
            v8, vsc = quantize_kv(vs)
            k_pages = {
                "q8": k_pages["q8"].at[:, pid, :count].set(k8),
                "scale": k_pages["scale"].at[:, pid, :count].set(ksc),
            }
            v_pages = {
                "q8": v_pages["q8"].at[:, pid, :count].set(v8),
                "scale": v_pages["scale"].at[:, pid, :count].set(vsc),
            }
        else:
            k_pages = k_pages.at[:, pid, :count].set(ks.astype(k_pages.dtype))
            v_pages = v_pages.at[:, pid, :count].set(vs.astype(v_pages.dtype))
    return PagedKVCache(k_pages, v_pages, bt)
