"""Token sampling: greedy, temperature, top-k, top-p — jit-safe.

All branches are computed with masking (no Python control flow on traced
values). Semantics match the conventional engine behavior users calibrate
against: top-k filters first, then top-p operates on the *renormalized*
post-top-k distribution; the most-likely token always survives (so
top_p=0.0 degrades to greedy, not to token 0).

Per-request reproducibility: `sample` takes per-row uint32 seeds and the
current position; the row key is fold_in(PRNGKey(seed), position), so a
request with a fixed seed replays identically regardless of batch-mates.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling config (host-side; arrays built per batch).

    `stop` holds stop *strings*; they operate on detokenized text and are
    enforced by the server layer (kubeai_tpu.engine.server), not here —
    the engine core works purely in token space (EOS token ids).
    """

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    max_tokens: int = 16
    stop: tuple[str, ...] = ()
    seed: int | None = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


# Sampling candidate pool: top-k and the nucleus are computed within the
# MAX_TOP_K most likely tokens. Bounds the per-step cost to one
# lax.top_k(64) instead of two full-vocab sorts (a ~10x decode-step win on
# 128k vocabs); the same cap is standard in serving engines.
MAX_TOP_K = 64


def sample(
    logits: jnp.ndarray,  # [B, V] float32
    seeds: jnp.ndarray,  # [B] uint32 per-request seeds
    positions: jnp.ndarray,  # [B] int32 current position (per-step entropy)
    temperature: jnp.ndarray,  # [B] (0 = greedy)
    top_k: jnp.ndarray,  # [B] int32 (0 = off; capped at MAX_TOP_K)
    top_p: jnp.ndarray,  # [B] float32 (1 = off)
) -> jnp.ndarray:
    """Vectorized per-request sampling. Returns [B] int32 token ids."""
    B, V = logits.shape
    K = min(MAX_TOP_K, V)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    vals, idxs = jax.lax.top_k(scaled, K)  # [B, K] descending
    # top-k filter within the candidate pool.
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, K), K)  # [B]
    keep_k = jnp.arange(K)[None, :] < k_eff[:, None]

    # top-p (nucleus) over the RENORMALIZED post-top-k distribution.
    kvals = jnp.where(keep_k, vals, -jnp.inf)
    probs = jax.nn.softmax(kvals, axis=-1)
    cumsum = jnp.cumsum(probs, axis=-1)
    keep_p = cumsum - probs < top_p[:, None]
    keep = keep_k & keep_p
    keep = keep.at[:, 0].set(True)  # top-1 always survives
    masked = jnp.where(keep, kvals, -jnp.inf)

    def _row(seed, pos, row_logits):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.categorical(key, row_logits)

    choice = jax.vmap(_row)(seeds, positions, masked)  # [B] in [0, K)
    sampled = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0]
    return jnp.where(
        temperature <= 0.0, greedy_tok, sampled.astype(jnp.int32)
    )
