"""Token sampling: greedy, temperature, top-k, top-p — jit-safe.

All branches are computed with masking (no Python control flow on traced
values). Semantics match the conventional engine behavior users calibrate
against: top-k filters first, then top-p operates on the *renormalized*
post-top-k distribution; the most-likely token always survives (so
top_p=0.0 degrades to greedy, not to token 0).

Per-request reproducibility: `sample` takes per-row uint32 seeds and the
current position; the row key is fold_in(PRNGKey(seed), position), so a
request with a fixed seed replays identically regardless of batch-mates.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling config (host-side; arrays built per batch).

    `stop` holds stop *strings*; they operate on detokenized text and are
    enforced by the server layer (kubeai_tpu.engine.server), not here —
    the engine core works purely in token space (EOS token ids).
    """

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    max_tokens: int = 16
    stop: tuple[str, ...] = ()
    seed: int | None = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


def sample(
    logits: jnp.ndarray,  # [B, V] float32
    seeds: jnp.ndarray,  # [B] uint32 per-request seeds
    positions: jnp.ndarray,  # [B] int32 current position (per-step entropy)
    temperature: jnp.ndarray,  # [B] (0 = greedy)
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] float32 (1 = off)
) -> jnp.ndarray:
    """Vectorized per-request sampling. Returns [B] int32 token ids."""
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask logits below the k-th largest (per row).
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B, 1]
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p (nucleus) over the RENORMALIZED post-top-k distribution.
    sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]  # -inf tail for masked
    probs_sorted = jax.nn.softmax(sorted2, axis=-1)
    cumsum = jnp.cumsum(probs_sorted, axis=-1)
    inside = cumsum - probs_sorted < top_p[:, None]
    inside = inside.at[:, 0].set(True)  # top-1 always survives
    cutoff = jnp.where(inside, sorted2, jnp.inf)
    cutoff_val = jnp.min(cutoff, axis=-1, keepdims=True)
    scaled = jnp.where(scaled >= cutoff_val, scaled, -jnp.inf)

    def _row(seed, pos, row_logits):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.categorical(key, row_logits)

    sampled = jax.vmap(_row)(seeds, positions, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled)
