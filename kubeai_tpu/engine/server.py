"""The engine's HTTP serving front — what runs inside a KubeAITPU engine
Pod (rendered by kubeai_tpu.operator.engines.kubeai_tpu_engine).

Endpoints (OpenAI-compatible surface + the admin seam the operator uses):
  POST /v1/chat/completions   (stream=true → SSE chunks)
  POST /v1/completions
  GET  /v1/models
  GET  /health                ← readiness/liveness probes
  GET  /metrics               ← Prometheus text (engine counters)
  GET  /v1/state              ← admin snapshot (occupancy, spec/prefix stats)
  POST /v1/load_lora_adapter  ← operator adapter orchestration
  POST /v1/unload_lora_adapter   (reference: internal/vllmclient/client.go)

Serving loop: a dedicated thread drives Engine.step() continuously while
work exists; HTTP handler threads enqueue requests and consume per-request
token queues (streaming starts on the first decoded chunk).

Run: python -m kubeai_tpu.engine.server --model-url ... [--tpu-topology 2x2]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler

from kubeai_tpu.httpserver import DeepBacklogHTTPServer

from kubeai_tpu.engine.engine import (
    Engine,
    EngineConfig,
    EngineDraining,
    StepEvent,
)
from kubeai_tpu.engine.sampling import SamplingParams
from kubeai_tpu.metrics import flightrecorder, tracing
from kubeai_tpu.engine.tokenizer import Tokenizer, load_tokenizer
from kubeai_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    ObjstoreRetries,
    Registry,
    TracingDroppedSpans,
)
from kubeai_tpu.scheduling import (
    DeadlineInfeasible,
    PRIORITY_CLASSES,
)
from kubeai_tpu.utils import retryafter

logger = logging.getLogger(__name__)


# Request-phase latencies: cover sub-ms tiny-model CPU tests through the
# 600s request budget.
REQUEST_LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)
# Inter-token gaps sit orders of magnitude below request latencies —
# fused decode chunks emit most tokens ~0 apart, chunk boundaries land in
# the ms range, and anything past 2.5s is a stall worth seeing.
ITL_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class EngineMetrics:
    def __init__(self):
        self.registry = Registry()
        self.generated_tokens = Counter(
            "kubeai_engine_generated_tokens_total",
            "Tokens generated.",
            self.registry,
        )
        self.prompt_tokens = Counter(
            "kubeai_engine_prompt_tokens_total",
            "Prompt tokens processed.",
            self.registry,
        )
        self.active_requests = Gauge(
            "kubeai_engine_active_requests",
            "Requests currently queued or decoding.",
            self.registry,
        )
        self.requests_total = Counter(
            "kubeai_engine_requests_total", "Requests served.", self.registry
        )
        self.slots_active = Gauge(
            "kubeai_engine_slots_active",
            "Decode slots currently occupied.",
            self.registry,
        )
        self.requests_pending = Gauge(
            "kubeai_engine_requests_pending",
            "Requests queued for a free slot.",
            self.registry,
        )
        self.spec_proposed = Gauge(
            "kubeai_engine_spec_proposed_tokens_total",
            "Speculative tokens proposed (prompt-lookup or draft).",
            self.registry,
        )
        self.spec_accepted = Gauge(
            "kubeai_engine_spec_accepted_tokens_total",
            "Speculative tokens accepted by verify.",
            self.registry,
        )
        # Monotonically-growing totals exported with COUNTER semantics
        # (they were Gauges once — a `_total` metric that can be `set()`
        # backward breaks every rate() over it); sync_engine folds the
        # engine's cumulative stats in as deltas.
        self.prefix_hit_tokens = Counter(
            "kubeai_engine_prefix_cached_tokens_total",
            "Prompt tokens served from the prefix cache (skipped prefill).",
            self.registry,
        )
        self.prefix_prompt_tokens = Counter(
            "kubeai_engine_prefix_prompt_tokens_total",
            "Prompt tokens seen by prefix-cache admission.",
            self.registry,
        )
        # -- disaggregated serving: KV handoff transfer ---------------------
        self.kv_handoffs = Counter(
            "kubeai_engine_kv_handoffs_total",
            "KV handoffs by direction: exported after prefill (prefill "
            "role) / imported into decode slots (decode role).",
            self.registry,
        )
        self.kv_transfer_bytes = Counter(
            "kubeai_engine_kv_transfer_bytes_total",
            "Serialized KV handoff bytes moved, by direction "
            "(export = pushed to a decode pool, import = received on "
            "/v1/kv/import).",
            self.registry,
        )
        self.kv_transfer_seconds = Histogram(
            "kubeai_engine_kv_transfer_seconds",
            "Wall time of one KV handoff transfer (chunked HTTP push or "
            "receive), by direction.",
            self.registry,
            buckets=REQUEST_LATENCY_BUCKETS_S,
        )
        # -- cluster KV-sharing tier (peer prefix fetch / objstore spill) ---
        self.kv_fetch_attempts = Counter(
            "kubeai_kv_fetch_attempts_total",
            "Prefix KV fetches attempted, by source (peer = /v1/kv/export "
            "on the holding replica, spill = objstore fill).",
            self.registry,
        )
        self.kv_fetch_bytes = Counter(
            "kubeai_kv_fetch_bytes_total",
            "Serialized prefix-page bytes fetched from peers or the "
            "objstore spill tier instead of recomputing prefill.",
            self.registry,
        )
        self.kv_fetch_failures = Counter(
            "kubeai_kv_fetch_failures_total",
            "Prefix KV fetches that failed (timeout, peer death, "
            "malformed blob, pool refusal) and fell back to recompute, "
            "by source.",
            self.registry,
        )
        self.kv_share_pages = Counter(
            "kubeai_engine_kv_share_pages_total",
            "Cluster KV-sharing page movement by direction: exported "
            "(served to a peer), imported (seeded from a peer), spilled "
            "(evicted to objstore), filled (restored from objstore).",
            self.registry,
        )
        self.role_info = Gauge(
            "kubeai_engine_role",
            "1 for this replica's serving role label "
            "(prefill/decode/unified).",
            self.registry,
        )
        self.slot_capacity = Gauge(
            "kubeai_engine_slot_capacity",
            "Configured decode slots — with kubeai_engine_batch_size this "
            "gives the autoscaler slot occupancy.",
            self.registry,
        )
        # -- request-lifecycle latency histograms --------------------------
        self.queue_wait = Histogram(
            "kubeai_engine_queue_wait_seconds",
            "Time a request waited in the pending queue before its "
            "prefill was dispatched.",
            self.registry,
            buckets=REQUEST_LATENCY_BUCKETS_S,
        )
        self.prefill = Histogram(
            "kubeai_engine_prefill_seconds",
            "Prefill dispatch to first sampled token (compute only; "
            "queue wait excluded).",
            self.registry,
            buckets=REQUEST_LATENCY_BUCKETS_S,
        )
        self.ttft = Histogram(
            "kubeai_engine_ttft_seconds",
            "Engine time-to-first-token: request enqueue to first sampled "
            "token (queue wait + prefill).",
            self.registry,
            buckets=REQUEST_LATENCY_BUCKETS_S,
        )
        self.itl = Histogram(
            "kubeai_engine_inter_token_latency_seconds",
            "Gap between consecutive emitted tokens of one request. "
            "Tokens inside one fused decode chunk surface together, so "
            "the distribution is bimodal: ~0 intra-chunk, the device-step "
            "time at chunk boundaries.",
            self.registry,
            buckets=ITL_BUCKETS_S,
        )
        self.e2e = Histogram(
            "kubeai_engine_e2e_seconds",
            "Request enqueue to final token for completed (stop/length) "
            "requests; cancellations are excluded.",
            self.registry,
            buckets=REQUEST_LATENCY_BUCKETS_S,
        )
        self._timing_hist = {
            "queue_wait": self.queue_wait,
            "prefill": self.prefill,
            "ttft": self.ttft,
            "itl": self.itl,
            "e2e": self.e2e,
        }
        # -- per-decode-step engine-loop gauges ----------------------------
        self.batch_size = Gauge(
            "kubeai_engine_batch_size",
            "Running batch size (occupied decode slots) at the last "
            "engine step.",
            self.registry,
        )
        self.kv_utilization = Gauge(
            "kubeai_engine_kv_cache_utilization",
            "Fraction of KV-cache capacity in use (pages allocated / "
            "pool, or token positions / slot capacity).",
            self.registry,
        )
        self.kv_cache_bytes = Gauge(
            "kubeai_engine_kv_cache_bytes",
            "Resident bytes of the KV-cache pool (pages + quantization "
            "scales) — int8 pools report roughly half a bf16 pool of "
            "equal token capacity.",
            self.registry,
        )
        self.kv_quant_enabled = Gauge(
            "kubeai_engine_kv_quant_enabled",
            "1 when the paged KV cache stores int8 quantized pages "
            "(kv_dtype=int8), else 0.",
            self.registry,
        )
        self.kv_quant_capacity_factor = Gauge(
            "kubeai_engine_kv_quant_capacity_factor",
            "Slot-capacity multiplier of the configured KV dtype vs bf16 "
            "at equal HBM budget (2D/(D+4) under int8, 1.0 under bf16) — "
            "what the autoscaler and capacity planner scale the replica's "
            "effective KV capacity by.",
            self.registry,
        )
        self.tokens_per_step = Gauge(
            "kubeai_engine_tokens_per_step",
            "Tokens emitted by the last engine step (all requests).",
            self.registry,
        )
        self.step_duration = Gauge(
            "kubeai_engine_step_duration_seconds",
            "Wall duration of the last engine step's decode dispatch + "
            "fetch.",
            self.registry,
        )
        # -- engine step profiler (kubeai_tpu/fleet/profiler) ---------------
        self.step_phase = Histogram(
            "kubeai_engine_step_phase_seconds",
            "Wall time per engine-step phase (label `phase`: schedule / "
            "prefill / decode / dispatch / overlap_idle / readback / "
            "sample / kv_transfer) — the per-phase answer to 'why is ITL "
            "high'. decode is the async jit DISPATCH; the device wait "
            "surfaces as overlap_idle at reap (shrinking toward zero "
            "under the overlapped step pipeline) and the token transfer "
            "as readback.",
            self.registry,
            buckets=ITL_BUCKETS_S,
        )
        self.tracing_dropped = TracingDroppedSpans(
            "kubeai_tracing_dropped_spans_total",
            "Spans dropped by the OTLP exporter (queue full or exporter "
            "thread dead) instead of blocking the request path.",
            self.registry,
        )
        # -- scheduler queue-pressure signal (per priority class) ----------
        self.queue_depth = Gauge(
            "kubeai_engine_queue_depth",
            "Requests waiting in the scheduler, per priority class — the "
            "autoscaler's queue-pressure depth signal.",
            self.registry,
        )
        self.queue_oldest_wait = Gauge(
            "kubeai_engine_queue_oldest_wait_seconds",
            "Age of the oldest waiting request per priority class — the "
            "autoscaler's queue-pressure staleness signal.",
            self.registry,
        )
        self.queue_admitted = Gauge(
            "kubeai_engine_queue_admitted_total",
            "Requests dispatched out of the scheduler per priority class.",
            self.registry,
        )
        self.queue_shed = Gauge(
            "kubeai_engine_queue_shed_total",
            "Requests shed at enqueue (infeasible deadline) per priority "
            "class.",
            self.registry,
        )
        self.queue_mean_wait = Gauge(
            "kubeai_engine_queue_mean_wait_seconds",
            "Mean queue wait of dispatched requests per priority class.",
            self.registry,
        )
        self.sched_service_rate = Gauge(
            "kubeai_engine_sched_service_rate",
            "Scheduler drain-rate estimate (requests/second) used for "
            "deadline feasibility and the computed Retry-After.",
            self.registry,
        )
        # -- graceful drain ------------------------------------------------
        self.draining = Gauge(
            "kubeai_engine_draining",
            "1 while the server is draining (refusing new work, "
            "completing in-flight generations), else 0.",
            self.registry,
        )
        self.drain_terminated = Gauge(
            "kubeai_engine_drain_terminated_requests_total",
            "In-flight requests terminated because the drain budget "
            "expired before they completed.",
            self.registry,
        )
        # -- step watchdog ---------------------------------------------------
        self.watchdog_wedged = Gauge(
            "kubeai_engine_watchdog_wedged",
            "1 after the step watchdog detected a hung device step "
            "(health flipped, restart requested), else 0.",
            self.registry,
        )
        self.watchdog_stalls = Counter(
            "kubeai_engine_watchdog_stalls_total",
            "Hung-device-step detections by the engine watchdog.",
            self.registry,
        )
        # -- cold start: snapshot restore-first boot (engine/coldstart) -----
        self.coldstart_phase = Gauge(
            "kubeai_coldstart_phase_seconds",
            "Wall time of each boot phase (label `phase`: fetch/restore "
            "on the snapshot path, load on the full HF-conversion path, "
            "compile/warmup on both) — the per-phase answer to 'why was "
            "this replica slow to Ready'.",
            self.registry,
        )
        self.coldstart_total = Gauge(
            "kubeai_coldstart_total_seconds",
            "End-to-end boot wall time (model resolve through warm-up) — "
            "the measured cold-start cost the capacity planner prices "
            "into prewarm and preemption choices.",
            self.registry,
        )
        self.coldstart_restored = Gauge(
            "kubeai_coldstart_restored",
            "1 when this boot restored the engine snapshot (params + "
            "compilation cache), 0 on the full load path.",
            self.registry,
        )
        self.coldstart_events = Counter(
            "kubeai_coldstart_snapshot_events_total",
            "Snapshot lifecycle events at boot (label `event`: restored, "
            "published, absent, mismatch, error). `mismatch` means the "
            "stored fingerprint disagreed and the boot fell back to full "
            "load — a stale layout is never served.",
            self.registry,
        )
        self.objstore_retries = ObjstoreRetries(
            "kubeai_objstore_retries_total",
            "Object-store requests retried after a transient failure "
            "(5xx/429, connection reset, short read) across every "
            "client in the process.",
            self.registry,
        )

    def record_coldstart(self, cold_start: dict) -> None:
        """Fold a ColdStartTracker snapshot into the boot metrics."""
        for phase, secs in (cold_start.get("phases") or {}).items():
            self.coldstart_phase.set(secs, phase=phase)
        self.coldstart_total.set(float(cold_start.get("total_s", 0.0)))
        self.coldstart_restored.set(1 if cold_start.get("restored") else 0)
        for ev in cold_start.get("events") or ():
            self.coldstart_events.inc(event=ev)

    def observe_timing(
        self, kind: str, seconds: float, exemplar: str | None = None
    ) -> None:
        h = self._timing_hist.get(kind)
        if h is not None:
            h.observe(seconds, exemplar=exemplar)

    def sync_engine(self, engine) -> None:
        """Snapshot engine serving state (the engine owns these counters;
        it records plain host-side values and this method moves them into
        the registry). Called from the serve loop after each step AND at
        /metrics scrape time, so the histograms are current even when the
        loop has gone idle."""
        snap = engine_state_snapshot(engine)
        self.slots_active.set(snap["slots_active"])
        self.requests_pending.set(snap["requests_pending"])
        stats = snap["spec_stats"]
        if stats:
            self.spec_proposed.set(stats["proposed"])
            self.spec_accepted.set(stats["accepted"])
        pstats = snap["prefix_stats"]
        if pstats:
            # Counter semantics over cumulative engine-side stats: fold in
            # the delta since the last sync (never set, never backward).
            self.prefix_hit_tokens.inc(
                max(0.0, pstats["hit_tokens"] - self.prefix_hit_tokens.get())
            )
            self.prefix_prompt_tokens.inc(
                max(
                    0.0,
                    pstats["prompt_tokens"]
                    - self.prefix_prompt_tokens.get(),
                )
            )
        inner = getattr(engine, "inner", engine)  # LockstepEngine proxies
        dstats = getattr(inner, "disagg_stats", None)
        if dstats:
            for direction, count_key, bytes_key in (
                ("export", "exported", "exported_bytes"),
                ("import", "imported", "imported_bytes"),
            ):
                self.kv_handoffs.inc(
                    max(
                        0.0,
                        dstats[count_key]
                        - self.kv_handoffs.get(direction=direction),
                    ),
                    direction=direction,
                )
                self.kv_transfer_bytes.inc(
                    max(
                        0.0,
                        dstats[bytes_key]
                        - self.kv_transfer_bytes.get(direction=direction),
                    ),
                    direction=direction,
                )
        kstats = getattr(inner, "kv_share_stats", None)
        if kstats:
            for direction, key in (
                ("exported", "exported_pages"),
                ("imported", "imported_pages"),
                ("spilled", "spilled_pages"),
                ("filled", "filled_pages"),
            ):
                self.kv_share_pages.inc(
                    max(
                        0.0,
                        kstats[key]
                        - self.kv_share_pages.get(direction=direction),
                    ),
                    direction=direction,
                )
        slots = getattr(getattr(inner, "cfg", None), "num_slots", None)
        if slots is not None:
            self.slot_capacity.set(slots)
        kv_info = snap.get("kv_cache") or {}
        if kv_info:
            self.kv_cache_bytes.set(kv_info.get("pool_bytes", 0))
            self.kv_quant_enabled.set(
                1.0 if kv_info.get("quantized") else 0.0
            )
            self.kv_quant_capacity_factor.set(
                kv_info.get("capacity_factor", 1.0)
            )
        drain = getattr(inner, "drain_timing", None)
        if drain is not None:
            for rec in drain():
                self.observe_timing(
                    rec[0], rec[1],
                    exemplar=rec[2] if len(rec) > 2 else None,
                )
        prof = getattr(inner, "profiler", None)
        if prof is not None:
            for phase, seconds in prof.drain():
                self.step_phase.observe(seconds, phase=phase)
        step_stats = snap["last_step"]
        if step_stats:
            self.batch_size.set(step_stats.get("batch_size", 0))
            self.tokens_per_step.set(step_stats.get("tokens", 0))
            self.step_duration.set(step_stats.get("duration_s", 0.0))
        self.kv_utilization.set(snap["kv_utilization"])
        sched = snap.get("scheduler") or {}
        for cls, stats in (sched.get("classes") or {}).items():
            self.queue_depth.set(stats["depth"], **{"class": cls})
            self.queue_oldest_wait.set(
                stats["oldest_wait_s"], **{"class": cls}
            )
            self.queue_admitted.set(
                stats["admitted_total"], **{"class": cls}
            )
            self.queue_shed.set(stats["shed_total"], **{"class": cls})
            self.queue_mean_wait.set(
                stats["mean_queue_wait_s"], **{"class": cls}
            )
        if sched:
            self.sched_service_rate.set(sched.get("service_rate", 0.0))


def engine_state_snapshot(engine) -> dict:
    """Serving-state snapshot shared by /metrics and /v1/state. Occupancy
    comes from the OUTER engine (LockstepEngine's num_pending includes
    adds buffered for the next broadcast — the same counts admission
    uses); spec/prefix stats live only on the inner engine."""
    inner = getattr(engine, "inner", engine)  # LockstepEngine proxies
    kvu = getattr(inner, "kv_utilization", None)
    sched = getattr(inner, "scheduler", None)
    kv_info = getattr(inner, "kv_cache_info", None)
    return {
        "slots_active": engine.num_active,
        "requests_pending": engine.num_pending,
        "kv_utilization": kvu() if kvu is not None else 0.0,
        # KV dtype / capacity block: quantized replicas advertise their
        # capacity factor here so the autoscaler and capacity planner
        # size against REAL capacity, not the bf16 assumption.
        "kv_cache": kv_info() if kv_info is not None else {},
        "last_step": dict(getattr(inner, "last_step_stats", {}) or {}),
        "spec_stats": dict(getattr(inner, "spec_stats", {}) or {}),
        "prefix_stats": dict(getattr(inner, "prefix_stats", {}) or {}),
        "kv_share": dict(getattr(inner, "kv_share_stats", {}) or {}),
        # Queue-pressure snapshot: per-class depth/oldest-wait/admitted/
        # shed plus drain rate and the current computed retry hint.
        "scheduler": sched.snapshot() if sched is not None else {},
    }


class EngineServer:
    def __init__(
        self,
        engine: Engine,
        tokenizer: Tokenizer,
        served_model_name: str,
        host: str = "0.0.0.0",
        port: int = 8000,
        adapter_fetcher=None,  # (name, url) -> adapter weight tree
        max_queue: int = 256,
        request_timeout: float = 600.0,
        default_priority: str = "standard",
        max_deadline_ms: int = 0,
        drain_timeout: float = 30.0,
        role: str = "unified",
        max_transfer_mb: int = 0,
        transfer_timeout: float = 30.0,
        watchdog_timeout: float = 0.0,
        watchdog_action=None,
        kv_sharing: bool = False,
        kv_fetch_timeout: float = 5.0,
        kv_spill_store=None,
        cold_start: dict | None = None,
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.served_model_name = served_model_name
        self.metrics = EngineMetrics()
        # Always-on flight recorder: scheduler admissions/sheds,
        # preemptions, watchdog/step anomalies land in bounded rings
        # surfaced on /v1/state (the fleet plane bundles its own rings;
        # the engine's travel with its state snapshot).
        self.recorder = flightrecorder.FlightRecorder(ring_size=128)
        engine.on_preempt = self._note_preempt
        # Boot cold-start record (ColdStartTracker.snapshot()): surfaced
        # on /v1/state so the fleet aggregator carries each replica's
        # measured cold-start cost to the planner, and folded into the
        # kubeai_coldstart_* metrics.
        self.cold_start = dict(cold_start or {})
        if self.cold_start:
            self.metrics.record_coldstart(self.cold_start)
        # Disaggregated serving role: "prefill" turns every generate into
        # prefill→handoff (pushed to the decode address the router names);
        # "decode"/"unified" accept handoffs on /v1/kv/import and admit
        # them via X-Disagg-Handoff. "unified" also serves normally — the
        # router's fallback pool.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        self.role = role
        self.max_transfer_bytes = max(0, int(max_transfer_mb)) * 1024 * 1024
        self.transfer_timeout = transfer_timeout
        from kubeai_tpu.disagg.transport import HandoffStore

        self._handoffs = HandoffStore()
        # Cluster KV-sharing tier: publish prefix holdings in /v1/state,
        # serve peers' partial-chain fetches on /v1/kv/export, and pull
        # missing prefix pages from the X-KV-Source peer (or the objstore
        # spill store) before admission instead of recomputing prefill.
        self.kv_sharing = bool(kv_sharing)
        self.kv_fetch_timeout = kv_fetch_timeout
        self.kv_spill = kv_spill_store
        if self.kv_spill is not None:
            spill_wire = getattr(engine, "enable_kv_spill", None)
            if spill_wire is None:
                inner = getattr(engine, "inner", None)
                spill_wire = getattr(inner, "enable_kv_spill", None)
            if spill_wire is not None:
                spill_wire(self.kv_spill)
        self.metrics.role_info.set(1, role=role)
        self.adapter_fetcher = adapter_fetcher
        # Scheduling defaults (CRD `scheduling:` block, rendered as engine
        # flags): applied when the request carries no X-Priority /
        # X-Deadline-Ms headers; max_deadline_ms caps client deadlines.
        self.default_priority = default_priority
        self.max_deadline_ms = max_deadline_ms
        # Adapter name -> source path/url it was loaded from. A load for a
        # name whose source CHANGED reloads instead of short-circuiting.
        self._adapter_sources: dict[str, str] = {}
        self.max_queue = max_queue
        self.request_timeout = request_timeout
        self._subscribers: dict[int, queue.Queue] = {}
        self._sub_lock = threading.Lock()
        self._stop = threading.Event()
        self._work = threading.Event()
        # Graceful drain (SIGTERM / POST /v1/drain): refuse new work with
        # 503 + Retry-After, finish in-flight generations up to
        # drain_timeout, then terminate the stragglers cleanly.
        self.drain_timeout = drain_timeout
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._drain_started = 0.0
        self._drain_thread: threading.Thread | None = None
        # Step watchdog: a hung device step (work active, no step
        # progress past watchdog_timeout) flips /health and fires
        # watchdog_action — in production that exits nonzero so kubelet
        # restarts the pod; tests inject a recorder. 0 disables.
        self.watchdog_timeout = watchdog_timeout
        self._watchdog_action = watchdog_action
        self._wedged = False
        self._watchdog_thread: threading.Thread | None = None
        self._loop_thread = threading.Thread(target=self._serve_loop, daemon=True)

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            _last_status = 200  # recorded for the request span

            def _json(self, status: int, payload: dict, headers: dict | None = None):
                self._last_status = status
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/health":
                    if outer.draining:
                        # The LB's health view must eject this replica
                        # while the drain runs.
                        return self._json(
                            503, {"status": "draining", "draining": True}
                        )
                    if outer.healthy():
                        return self._json(200, {"status": "ok"})
                    if outer._wedged:
                        return self._json(
                            503, {"status": "wedged", "wedged": True}
                        )
                    return self._json(503, {"status": "unhealthy"})
                if path == "/v1/drain":
                    # kubelet preStop httpGet can only send GET — the
                    # drain trigger accepts it alongside the POST form.
                    return self._json(202, outer.begin_drain())
                if path == "/metrics":
                    outer.metrics.sync_engine(outer.engine)
                    body = outer.metrics.registry.expose().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/v1/models":
                    data = [
                        {
                            "id": outer.served_model_name,
                            "object": "model",
                            "owned_by": "kubeai-tpu",
                        }
                    ] + [
                        {"id": a, "object": "model", "owned_by": "kubeai-tpu"}
                        for a in outer.engine.loaded_adapters()
                    ]
                    return self._json(200, {"object": "list", "data": data})
                if path == "/v1/state":
                    # Admin snapshot of serving state: what an operator
                    # (or a human) polls to see batching occupancy and
                    # the speculation/prefix-cache effectiveness without
                    # parsing Prometheus text.
                    return self._json(
                        200,
                        {
                            "model": outer.served_model_name,
                            "healthy": outer.healthy(),
                            "draining": outer.draining,
                            "role": outer.role,
                            "pending_handoffs": len(outer._handoffs),
                            "adapters": outer.engine.loaded_adapters(),
                            "kv_sharing": outer.kv_sharing,
                            # Held page-hash chains (hex): the fleet
                            # aggregator joins these into the cluster
                            # who-holds-which-prefix map. Computed only
                            # here (not per step) — it walks the whole
                            # registered-page table.
                            "kv_holdings": outer.kv_holdings(),
                            # Boot cold-start record: restored-or-not,
                            # per-phase timings, snapshot fingerprint.
                            # The aggregator copies this to the planner
                            # as the model's measured cold-start cost.
                            "cold_start": outer.cold_start,
                            # Last-request-per-bucket exemplars: the
                            # "rid-<n>" tags that let an operator jump
                            # from a latency bucket to the request that
                            # last landed in it.
                            "exemplars": {
                                "ttft": outer.metrics.ttft.exemplars(),
                                "itl": outer.metrics.itl.exemplars(),
                            },
                            # Flight-recorder rings: the engine's
                            # discrete decisions (admits, sheds,
                            # preemptions, watchdog) in decision order.
                            "flight_recorder": (
                                outer.recorder.state_payload()
                            ),
                            **engine_state_snapshot(outer.engine),
                        },
                    )
                return self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                path = self.path.split("?")[0]
                if path == "/v1/kv/import":
                    # Binary (possibly chunked) upload: reads its own
                    # body — the JSON decode below must not touch it.
                    return outer._handle_kv_import(self)
                n = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw or b"{}")
                except json.JSONDecodeError as e:
                    return self._json(
                        400, {"error": {"message": f"bad JSON: {e}"}}
                    )
                # Continue the trace the operator's proxy started (W3C
                # traceparent), so one trace spans front door → engine.
                # The propagated X-Request-Id lands on the span: one id
                # follows the request front door → proxy attempt → engine.
                attrs = {"http.route": path}
                req_id = self.headers.get("X-Request-Id")
                if req_id:
                    attrs["request.id"] = req_id
                span = tracing.tracer().start_span(
                    f"engine {path}",
                    parent=tracing.parse_traceparent(
                        self.headers.get("traceparent")
                    ),
                    kind=tracing.KIND_SERVER,
                    attributes=attrs,
                )
                self.current_span = span
                self._last_status = 200
                try:
                    try:
                        if path == "/v1/drain":
                            return self._json(202, outer.begin_drain())
                        if path == "/v1/profile":
                            return outer._handle_profile(self, body)
                        if path == "/v1/kv/export":
                            return outer._handle_kv_export(self, body)
                        if path == "/v1/chat/completions":
                            return outer._handle_generate(self, body, chat=True)
                        if path == "/v1/completions":
                            return outer._handle_generate(self, body, chat=False)
                        if path == "/v1/embeddings":
                            return outer._handle_embeddings(self, body)
                        if path == "/v1/load_lora_adapter":
                            return outer._handle_load_adapter(self, body)
                        if path == "/v1/unload_lora_adapter":
                            return outer._handle_unload_adapter(self, body)
                        return self._json(
                            404, {"error": {"message": "not found"}}
                        )
                    except BrokenPipeError as e:
                        span.set_attribute(
                            "http.status_code", self._last_status
                        )
                        span.end(error=str(e) or "client disconnected")
                        raise
                    except Exception as e:
                        logger.exception("handler error")
                        return self._json(
                            500, {"error": {"message": str(e)}}
                        )
                finally:
                    # Handlers signal errors via returned 4xx/5xx JSON,
                    # not exceptions — the span must reflect that, or
                    # every refused request traces as a healthy OK.
                    if not span.end_ns:
                        span.set_attribute(
                            "http.status_code", self._last_status
                        )
                        span.end(
                            error=f"HTTP {self._last_status}"
                            if self._last_status >= 400 else None
                        )

        self.httpd = DeepBacklogHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    # -- lifecycle ------------------------------------------------------------

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._loop_thread.start()
        self._http_thread.start()
        if self.watchdog_timeout > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True
            )
            self._watchdog_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        # Join the serve loop BEFORE anything else broadcasts (multihost
        # shutdown): a step() collective in flight from this thread must
        # finish first or two host-0 collectives interleave undefined.
        if self._loop_thread.is_alive():
            self._loop_thread.join(timeout=30)
        # shutdown() handshakes with serve_forever; on a never-started
        # server it would wait forever.
        if self._http_thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()

    # -- engine loop -----------------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.engine.has_work():
                    self._work.wait(timeout=0.01)
                    self._work.clear()
                    continue
                for ev in self.engine.step():
                    with self._sub_lock:
                        q = self._subscribers.get(ev.rid)
                    if q is not None:
                        q.put(ev)
                # Per-decode-step telemetry: drain the engine's latency
                # records into histograms and refresh the occupancy/KV
                # gauges while they are live (a scrape between steps then
                # sees the batch as it ran, not as it idles).
                self.metrics.sync_engine(self.engine)
                self._last_progress = time.monotonic()
            except Exception:
                # A dead serving loop must flip /health so the liveness
                # probe restarts the Pod (the blocking LB then stops
                # routing here) — failure detection parity with the
                # reference's probe design (engine_vllm.go liveness).
                logger.exception("serving loop crashed")
                self.recorder.record(
                    flightrecorder.STEP_ANOMALY, "engine",
                    target=self.served_model_name, reason="loop_crash",
                )
                self._loop_dead = True
                return

    _loop_dead = False
    _last_progress = 0.0

    def healthy(self) -> bool:
        return (
            not self._loop_dead
            and not self._wedged
            and not self._stop.is_set()
        )

    def _note_preempt(self, rid: int, client: str) -> None:
        self.recorder.record(
            flightrecorder.SCHED_PREEMPT, "engine_sched",
            target=self.served_model_name, trace_id=f"rid-{rid}",
            client=client or "",
        )

    # -- step watchdog ----------------------------------------------------------

    @property
    def wedged(self) -> bool:
        return self._wedged

    def _watchdog_loop(self) -> None:
        """Detect a hung device step: work is active but the serve loop
        made no step progress for watchdog_timeout. A crashed loop
        already flips /health (_loop_dead); this catches the worse case
        where step() never RETURNS — a wedged XLA dispatch or a dead
        remote-chip tunnel — which no exception handler can see. On
        detection /health flips (the LB ejects long before the circuit
        breaker could accumulate response-header timeouts) and
        watchdog_action runs (production: exit nonzero → kubelet
        restarts the pod)."""
        poll = max(0.01, min(self.watchdog_timeout / 4.0, 1.0))
        busy_since: float | None = None
        while not self._stop.wait(timeout=poll):
            try:
                busy = self.engine.has_work()
            except Exception:
                busy = False
            now = time.monotonic()
            if not busy:
                busy_since = None
                continue
            if busy_since is None:
                # Work just (re)appeared: stall time counts from here,
                # not from a _last_progress stamped before an idle gap.
                busy_since = now
            anchor = max(self._last_progress, busy_since)
            # Overlapped stepping: a dispatched-but-unreaped chunk IS
            # progress — the device is computing and the host will reap
            # on the next step — but only within its own reap deadline
            # (the same watchdog budget). An in-flight chunk older than
            # that means the reap itself is wedged (hung dispatch, dead
            # tunnel) and must still trip the restart.
            info_fn = getattr(self.engine, "inflight_info", None)
            if info_fn is not None:
                try:
                    info = info_fn()
                except Exception:
                    info = None
                if info:
                    dispatched_at = float(info.get("dispatched_at", 0.0))
                    if now - dispatched_at <= self.watchdog_timeout:
                        anchor = max(anchor, dispatched_at)
            stalled_for = now - anchor
            if stalled_for <= self.watchdog_timeout:
                continue
            self._wedged = True
            self.metrics.watchdog_wedged.set(1)
            self.metrics.watchdog_stalls.inc()
            self.recorder.record(
                flightrecorder.WATCHDOG, "engine",
                target=self.served_model_name,
                stalled_for_s=round(stalled_for, 3),
                active=self.engine.num_active,
                pending=self.engine.num_pending,
            )
            self.recorder.trigger(
                flightrecorder.TRIGGER_WATCHDOG,
                detail=(
                    f"no step progress for {stalled_for:.1f}s with "
                    f"work active"
                ),
            )
            logger.error(
                "watchdog: no engine step progress for %.1fs with work "
                "active (%d active, %d pending) — flipping /health and "
                "requesting restart",
                stalled_for, self.engine.num_active, self.engine.num_pending,
            )
            if self._watchdog_action is not None:
                try:
                    self._watchdog_action()
                except Exception:
                    logger.exception("watchdog action failed")
            return

    # -- graceful drain ---------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> dict:
        """Start the drain sequence (idempotent): stop admitting, let
        in-flight generations finish, terminate stragglers when the
        budget runs out. Returns the status payload /v1/drain answers."""
        if not self._draining.is_set():
            self._drain_started = time.monotonic()
            self._draining.set()
            self.metrics.draining.set(1)
            # Close the admission race at the engine too: a request that
            # slipped past the handler's check still gets refused.
            inner = getattr(self.engine, "inner", self.engine)
            begin = getattr(inner, "begin_drain", None)
            if begin is not None:
                begin()
            self._work.set()
            self._drain_thread = threading.Thread(
                target=self._drain_worker, daemon=True
            )
            self._drain_thread.start()
            logger.info(
                "drain started: %d active, %d pending, budget %.1fs",
                self.engine.num_active, self.engine.num_pending,
                self.drain_timeout,
            )
        return {
            "draining": True,
            "active": self.engine.num_active,
            "pending": self.engine.num_pending,
            "drain_timeout_s": self.drain_timeout,
            "elapsed_s": round(time.monotonic() - self._drain_started, 3),
        }

    def _drain_worker(self) -> None:
        deadline = self._drain_started + self.drain_timeout
        while time.monotonic() < deadline:
            with self._sub_lock:
                streams = len(self._subscribers)
            if (
                streams == 0
                and self.engine.num_active == 0
                and self.engine.num_pending == 0
            ):
                self._drained.set()
                logger.info(
                    "drain complete: all in-flight work finished in %.2fs",
                    time.monotonic() - self._drain_started,
                )
                return
            time.sleep(0.02)
        # Budget exhausted: terminate the remaining streams CLEANLY — a
        # kill sentinel per subscriber makes its collector emit a final
        # chunk and release the slot, instead of the process exit
        # snapping TCP connections mid-token.
        with self._sub_lock:
            leftovers = list(self._subscribers.items())
        for rid, sub in leftovers:
            self.engine.cancel(rid)
            sub.put(
                StepEvent(
                    rid=rid, token=-1, finished=True,
                    finish_reason="cancelled",
                )
            )
        if leftovers:
            self.metrics.drain_terminated.set(len(leftovers))
            logger.warning(
                "drain budget (%.1fs) expired: terminated %d in-flight "
                "request(s)", self.drain_timeout, len(leftovers),
            )
        # Give the collectors a moment to flush their final chunks.
        flush_deadline = time.monotonic() + 2.0
        while time.monotonic() < flush_deadline:
            with self._sub_lock:
                if not self._subscribers:
                    break
            time.sleep(0.02)
        self._drained.set()

    def wait_drained(self, timeout: float | None = None) -> bool:
        """Block until the drain sequence finished (True) or `timeout`
        elapsed (False). The process entrypoint exits on True."""
        return self._drained.wait(
            timeout=self.drain_timeout + 5.0 if timeout is None else timeout
        )

    def _drain_refusal(self, http):
        """503 for work arriving during drain: computed Retry-After (the
        remaining drain budget, jittered through the shared helper — by
        then kubelet has restarted us or the LB moved on) and
        Connection: close so the client's keep-alive doesn't pin a
        dying server."""
        remaining = retryafter.jittered(
            self._drain_started + self.drain_timeout - time.monotonic(),
            min_s=1.0,
        )
        http.close_connection = True
        return http._json(
            503,
            {
                "error": {"message": "server is draining, retry elsewhere"},
                "draining": True,
            },
            headers={
                "Retry-After": retryafter.format_header(remaining),
                "Connection": "close",
            },
        )

    # -- step profiling (kubeai_tpu/fleet/profiler) -----------------------------

    def _handle_profile(self, http, body: dict):
        """POST /v1/profile — capture an N-step per-phase timeline.

        Body (all optional): `steps` (how many step records to return,
        default 16), `fresh` (true = wait for that many NEW steps up to
        `timeout_s` before answering; false = answer from the ring
        immediately), `jax_trace` (additionally wrap the capture window
        in `jax.profiler.trace` when a real device is present — no-op
        safe on CPU, the response carries the trace dir or null)."""
        from kubeai_tpu.fleet.profiler import phase_totals

        inner = getattr(self.engine, "inner", self.engine)
        prof = getattr(inner, "profiler", None)
        if prof is None:
            return http._json(
                400,
                {"error": {"message": "engine exposes no step profiler"}},
            )
        steps = body.get("steps", 16)
        if (
            isinstance(steps, bool)
            or not isinstance(steps, int)
            or not 1 <= steps <= 10_000
        ):
            return http._json(
                400,
                {"error": {"message": "steps must be an int in 1..10000"}},
            )
        timeout_s = body.get("timeout_s", 10.0)
        if (
            isinstance(timeout_s, bool)
            or not isinstance(timeout_s, (int, float))
            or not 0 < timeout_s <= 120
        ):
            return http._json(
                400,
                {"error": {"message": "timeout_s must be in (0, 120]"}},
            )
        fresh = bool(body.get("fresh", False))
        trace_dir = None
        if body.get("jax_trace"):
            # Device-level tracing rides along when the runtime supports
            # it; on CPU (or a runtime without the profiler service) this
            # degrades to the host-side phase timeline alone.
            import tempfile

            try:
                import jax

                trace_dir = tempfile.mkdtemp(prefix="kubeai-profile-")
                jax.profiler.start_trace(trace_dir)
            except Exception:  # noqa: BLE001 — profiling must not 500
                trace_dir = None
        captured = 0
        try:
            if fresh:
                captured = prof.wait_for_steps(steps, float(timeout_s))
        finally:
            if trace_dir is not None:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001
                    trace_dir = None
        records = prof.recent(steps)
        return http._json(
            200,
            {
                "object": "engine.profile",
                "model": self.served_model_name,
                "steps_requested": steps,
                "steps_captured": captured if fresh else len(records),
                "steps_completed_total": prof.steps_completed,
                "phase_totals_s": phase_totals(records),
                "steps": records,
                "jax_trace_dir": trace_dir,
            },
        )

    # -- request handling -------------------------------------------------------

    def _resolve_model(self, requested: str) -> tuple[str, str | None] | None:
        """Returns (display_name, adapter_or_None), or None when the name
        matches neither the served model nor a loaded adapter. Engines
        receive the adapter name in the `model` field (the operator's
        apiutils rewrites it — reference: internal/apiutils/request.go:
        190-199); an adapter this replica hasn't loaded must 404 like
        vLLM's admin API does, not silently serve the base model."""
        if requested in self.engine.loaded_adapters():
            return requested, requested
        if not requested or requested == self.served_model_name:
            return self.served_model_name, None
        return None

    def _handle_generate(self, http, body: dict, chat: bool):
        if self._draining.is_set():
            return self._drain_refusal(http)
        if self.role == "prefill":
            # A prefill-role engine NEVER enters decode: every generate
            # becomes prefill → KV handoff pushed to the decode address
            # the router named.
            return self._handle_prefill_generate(http, body, chat)
        hid = (http.headers.get("X-Disagg-Handoff") or "").strip()
        if hid:
            return self._handle_decode_from_handoff(http, body, chat, hid)
        model_field = str(body.get("model") or self.served_model_name)
        resolved = self._resolve_model(model_field)
        if resolved is None:
            return http._json(
                404,
                {
                    "error": {
                        "message": f"model {model_field!r} not found "
                        "(not the served model and no such loaded adapter)"
                    }
                },
            )
        display, adapter = resolved
        # n > 1: independent choices as concurrent engine requests. JSON
        # integers only (OpenAI rejects non-integral n; int() would
        # silently truncate 2.9); None means the client omitted it.
        raw_n = body.get("n")
        if raw_n is None:
            n = 1
        elif isinstance(raw_n, bool) or not isinstance(raw_n, int):
            n = 0  # falls through to the 400 below
        else:
            n = raw_n
        if not 1 <= n <= 8:
            return http._json(
                400, {"error": {"message": "n must be an integer in 1..8"}}
            )
        # Continuation request (proxy stream resume after a replica
        # death): `kubeai_resume` carries the tokens another replica
        # already emitted plus how many CHARACTERS of their text reached
        # the client — the stream resumes exactly at that boundary.
        resume_tokens: list[int] = []
        resume_emitted: int | None = None
        raw_resume = body.get("kubeai_resume")
        if raw_resume is not None:
            err = self._validate_resume(raw_resume, n)
            if err is not None:
                return http._json(400, {"error": {"message": err}})
            resume_tokens = [int(t) for t in raw_resume["token_ids"]]
            if "emitted" in raw_resume:
                resume_emitted = int(raw_resume["emitted"])
        # Scheduling identity from headers (the front door and messenger
        # propagate these): priority class, admission deadline, WFQ
        # fairness key. Defaults come from the CRD scheduling block.
        try:
            priority, deadline_ms, sched_client = self._parse_scheduling(
                http.headers, adapter
            )
        except ValueError as e:
            return http._json(400, {"error": {"message": str(e)}})
        # Bounded admission: past this depth requests would only pile onto
        # the scheduler and blow the 600s budget anyway — shed early so
        # the LB retries another replica (reference front-door survives
        # 8000 conc because vLLM sheds; we do our own shedding). All n
        # choices count against the bound. The Retry-After is COMPUTED
        # (queue depth ÷ measured drain rate) and the body carries
        # per-class depths so clients and the LB can back off honestly.
        if self.engine.num_pending + n > self.max_queue:
            return self._shed_response(http, "engine queue full, retry later")

        if chat:
            messages = body.get("messages") or []
            prompt_ids = self.tokenizer.apply_chat_template(messages)
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            prompt_ids = self.tokenizer.encode(str(prompt))
        if not prompt_ids:
            prompt_ids = [0]

        room = self.engine.cfg.max_seq_len - len(prompt_ids) - 1
        if room <= 0:
            return http._json(
                400,
                {
                    "error": {
                        "message": (
                            f"prompt too long: {len(prompt_ids)} tokens "
                            f">= context {self.engine.cfg.max_seq_len}"
                        )
                    }
                },
            )
        if resume_tokens and len(resume_tokens) >= room:
            return http._json(
                400,
                {"error": {"message": (
                    f"resume prefix of {len(resume_tokens)} tokens leaves "
                    f"no room under context {self.engine.cfg.max_seq_len}"
                )}},
            )
        # Sampling-parameter validation: malformed values must 400 with a
        # clear message, never surface as a 500 traceback (and
        # max_tokens: 0 is invalid, not a silent default).
        try:
            sp = self._parse_sampling(body, room)
        except ValueError as e:
            return http._json(400, {"error": {"message": str(e)}})
        if resume_tokens and len(resume_tokens) >= sp.max_tokens:
            return http._json(
                400,
                {"error": {"message": (
                    f"resume prefix of {len(resume_tokens)} tokens >= "
                    f"max_tokens {sp.max_tokens}: nothing left to generate"
                )}},
            )
        if self.kv_sharing and adapter is None and not resume_tokens:
            # Peer/objstore KV prefix fetch BEFORE admission: on success
            # the pages sit unowned in the idle pool and the ordinary
            # prefix-hit admission path below adopts them — on any
            # failure this returns silently and prefill recomputes.
            # Base-model requests only: per-replica LoRA slot seeds make
            # adapter chains incomparable across replicas.
            self._maybe_fetch_prefix(http.headers, prompt_ids, deadline_ms)
        stream = bool(body.get("stream", False))
        # Each choice gets a derived seed so explicit-seed requests stay
        # deterministic AND diverse. With the prefix cache on, choices
        # 2..n hit choice 1's freshly registered prompt pages, so the
        # extra prefills are mostly free.
        import dataclasses as _dc

        reqs: list[tuple[int, queue.Queue, SamplingParams]] = []
        try:
            for i in range(n):
                sub_i: queue.Queue = queue.Queue()
                sp_i = (
                    sp if i == 0 or sp.seed is None
                    else _dc.replace(sp, seed=sp.seed + i)
                )

                def register(rid: int, _sub=sub_i) -> None:
                    # Runs under the engine lock, before the request is
                    # visible to step(): no StepEvent can be emitted
                    # unsubscribed.
                    with self._sub_lock:
                        self._subscribers[rid] = _sub

                # kwargs-gated so engine stand-ins (tests) that predate
                # continuation support keep working untouched.
                resume_kw = (
                    {"resume_tokens": resume_tokens}
                    if resume_tokens and i == 0 else {}
                )
                rid_i = self.engine.add_request(
                    prompt_ids, sp_i, adapter=adapter, on_admit=register,
                    priority=priority, client=sched_client,
                    deadline_ms=deadline_ms, **resume_kw,
                )
                reqs.append((rid_i, sub_i, sp_i))
        except DeadlineInfeasible as e:
            # Shed at enqueue: the deadline cannot be met given queue
            # state and the measured drain rate. Cancel any sibling
            # choices that did make it in.
            for rid_i, _, _ in reqs:
                self.engine.cancel(rid_i)
                with self._sub_lock:
                    self._subscribers.pop(rid_i, None)
            self.recorder.record(
                flightrecorder.SCHED_SHED, "engine_sched",
                target=self.served_model_name, priority=priority,
                deadline_ms=deadline_ms, reason=str(e),
            )
            return self._shed_response(
                http, str(e), retry_after=e.retry_after
            )
        except EngineDraining:
            # Drain began between the handler check and admission.
            for rid_i, _, _ in reqs:
                self.engine.cancel(rid_i)
                with self._sub_lock:
                    self._subscribers.pop(rid_i, None)
            return self._drain_refusal(http)
        except KeyError as e:
            # Adapter unloaded between _resolve_model and admission.
            for rid_i, _, _ in reqs:
                self.engine.cancel(rid_i)
                with self._sub_lock:
                    self._subscribers.pop(rid_i, None)
            return http._json(404, {"error": {"message": str(e)}})
        except ValueError as e:
            # Residual continuation validation (e.g. a resume prefix that
            # already ends at a stop token, or a multi-host replica).
            for rid_i, _, _ in reqs:
                self.engine.cancel(rid_i)
                with self._sub_lock:
                    self._subscribers.pop(rid_i, None)
            return http._json(400, {"error": {"message": str(e)}})
        # Metrics only after successful admission, so a failed add_request
        # can't drift the gauge or inflate the counters.
        self.metrics.requests_total.inc(model=display)
        self.metrics.active_requests.inc()
        self.metrics.prompt_tokens.inc(len(prompt_ids) * n)
        self.recorder.record(
            flightrecorder.SCHED_ADMIT, "engine_sched", target=display,
            trace_id=f"rid-{reqs[0][0]}" if reqs else "",
            priority=priority, choices=n,
        )
        self._work.set()
        t0 = time.monotonic()
        span = getattr(http, "current_span", None)
        try:
            if stream:
                self._stream_response(http, reqs, display, chat, t0=t0,
                                      span=span,
                                      resume_tokens=resume_tokens,
                                      resume_emitted=resume_emitted)
            else:
                self._unary_response(http, reqs, display, chat,
                                     len(prompt_ids),
                                     resume_tokens=resume_tokens,
                                     resume_emitted=resume_emitted)
        finally:
            # The duration the TTFT/e2e histograms see must also be
            # readable off the trace — spans and metrics have to agree.
            if span is not None and not span.end_ns:
                span.set_attribute(
                    "request.duration_s", time.monotonic() - t0
                )
            # Client gone / handler done: release the batch slots if any
            # request is still decoding (no-op after normal completion).
            for rid_i, _, _ in reqs:
                self.engine.cancel(rid_i)
                with self._sub_lock:
                    self._subscribers.pop(rid_i, None)
            self.metrics.active_requests.dec()

    # -- scheduling & validation helpers ---------------------------------------

    def _validate_resume(self, raw_resume, n: int) -> str | None:
        """Shape-check a `kubeai_resume` continuation block; returns a
        client-readable error string or None when valid."""
        if getattr(self.engine, "is_lockstep", False):
            return "stream resume is not supported on multi-host replicas"
        if not isinstance(raw_resume, dict):
            return "kubeai_resume must be an object"
        if n != 1:
            return "kubeai_resume requires n == 1"
        toks = raw_resume.get("token_ids")
        if not isinstance(toks, list) or not toks or not all(
            isinstance(t, int) and not isinstance(t, bool) for t in toks
        ):
            return "kubeai_resume.token_ids must be a non-empty int list"
        emitted = raw_resume.get("emitted")
        if emitted is not None and (
            isinstance(emitted, bool)
            or not isinstance(emitted, int)
            or emitted < 0
        ):
            return "kubeai_resume.emitted must be an int >= 0"
        return None

    def _scheduler(self):
        inner = getattr(self.engine, "inner", self.engine)
        return getattr(inner, "scheduler", None)

    def _parse_scheduling(self, headers, adapter):
        """Resolve (priority, deadline_ms, client) from request headers +
        CRD-defaulted server settings. Raises ValueError on malformed
        values (the caller answers 400)."""
        raw_prio = (headers.get("X-Priority") or "").strip().lower()
        if raw_prio and raw_prio not in PRIORITY_CLASSES:
            raise ValueError(
                f"X-Priority must be one of {'/'.join(PRIORITY_CLASSES)}, "
                f"got {raw_prio!r}"
            )
        priority = raw_prio or self.default_priority
        deadline_ms = None
        raw_ddl = (headers.get("X-Deadline-Ms") or "").strip()
        if raw_ddl:
            try:
                deadline_ms = float(raw_ddl)
            except ValueError:
                raise ValueError(
                    f"X-Deadline-Ms must be a number of milliseconds, "
                    f"got {raw_ddl!r}"
                )
            if deadline_ms <= 0:
                raise ValueError("X-Deadline-Ms must be > 0")
        if deadline_ms is None and self.max_deadline_ms > 0:
            # The CRD cap doubles as the default deadline: every request
            # gets feasibility-checked against the operator's bound.
            deadline_ms = float(self.max_deadline_ms)
        elif deadline_ms is not None and self.max_deadline_ms > 0:
            deadline_ms = min(deadline_ms, float(self.max_deadline_ms))
        # WFQ fairness key: explicit client id, else the adapter (tenant
        # workloads commonly map 1:1 to adapters), else one shared key.
        client = (headers.get("X-Client-Id") or "").strip() or (adapter or "")
        return priority, deadline_ms, client

    @staticmethod
    def _parse_sampling(body: dict, room: int) -> SamplingParams:
        """Validate OpenAI sampling fields; raises ValueError with a
        client-readable message on malformed input."""

        def _number(key, default, *, lo=None, hi=None, integer=False):
            raw = body.get(key)
            if raw is None:
                return default
            if isinstance(raw, bool) or not isinstance(raw, (int, float)):
                raise ValueError(f"{key} must be a number, got {raw!r}")
            if integer and not isinstance(raw, int):
                raise ValueError(f"{key} must be an integer, got {raw!r}")
            v = raw
            if lo is not None and v < lo:
                raise ValueError(f"{key} must be >= {lo}, got {v}")
            if hi is not None and v > hi:
                raise ValueError(f"{key} must be <= {hi}, got {v}")
            return v

        max_tokens = body.get("max_tokens")
        if max_tokens is None:
            max_tokens = body.get("max_completion_tokens")
        if max_tokens is None:
            max_tokens = 128
        elif isinstance(max_tokens, bool) or not isinstance(max_tokens, int):
            raise ValueError(
                f"max_tokens must be a positive integer, got {max_tokens!r}"
            )
        elif max_tokens < 1:
            # 0 is a client bug — defaulting it to 128 would silently
            # burn a slot for output the client said it doesn't want.
            raise ValueError(
                f"max_tokens must be >= 1, got {max_tokens}"
            )
        temperature = float(_number("temperature", 1.0, lo=0.0))
        top_p = float(_number("top_p", 1.0, hi=1.0))
        if top_p <= 0.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        top_k = int(_number("top_k", 0, lo=0, integer=True))
        return SamplingParams(
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            max_tokens=min(max_tokens, room),
            seed=body.get("seed"),
            stop=tuple(
                [body["stop"]] if isinstance(body.get("stop"), str)
                else body.get("stop") or []
            ),
        )

    # -- disaggregated serving (kubeai_tpu/disagg) ------------------------------

    def _handle_prefill_generate(self, http, body: dict, chat: bool):
        """Prefill role: tokenize → chunked prefill → export the paged-KV
        handoff → push it to the decode engine the router named
        (X-Disagg-Transfer) → answer a small JSON receipt the router
        turns into the decode hop."""
        from kubeai_tpu.disagg.transport import HTTPTransport, TransferError
        from kubeai_tpu.engine.engine import EngineBusy

        target = (http.headers.get("X-Disagg-Transfer") or "").strip()
        if not target:
            return http._json(
                400,
                {"error": {"message": (
                    "prefill-role engine requires X-Disagg-Transfer: "
                    "<decode host:port> (the router supplies it)"
                )}},
            )
        model_field = str(body.get("model") or self.served_model_name)
        resolved = self._resolve_model(model_field)
        if resolved is None:
            return http._json(
                404,
                {"error": {"message": f"model {model_field!r} not found"}},
            )
        display, adapter = resolved
        raw_n = body.get("n")
        if raw_n not in (None, 1):
            # n > 1 decodes n independent streams from ONE prefill; the
            # two-hop path hands off a single sampler state, so the
            # router routes multi-choice requests to the unified pool.
            return http._json(
                400,
                {"error": {"message":
                           "n > 1 is not supported on the disaggregated "
                           "path; use a unified endpoint"}},
            )
        if chat:
            messages = body.get("messages") or []
            prompt_ids = self.tokenizer.apply_chat_template(messages)
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            prompt_ids = self.tokenizer.encode(str(prompt))
        if not prompt_ids:
            prompt_ids = [0]
        room = self.engine.cfg.max_seq_len - len(prompt_ids) - 1
        if room <= 0:
            return http._json(
                400,
                {"error": {"message": (
                    f"prompt too long: {len(prompt_ids)} tokens >= "
                    f"context {self.engine.cfg.max_seq_len}"
                )}},
            )
        try:
            sp = self._parse_sampling(body, room)
            priority, _deadline, client = self._parse_scheduling(
                http.headers, adapter
            )
        except ValueError as e:
            return http._json(400, {"error": {"message": str(e)}})
        try:
            handoff = self.engine.export_handoff(
                prompt_ids, sp, adapter=adapter, client=client,
                priority=priority, model_name=display,
            )
        except EngineBusy as e:
            return self._shed_response(http, str(e))
        except EngineDraining:
            return self._drain_refusal(http)
        except KeyError as e:
            return http._json(404, {"error": {"message": str(e)}})
        self.metrics.requests_total.inc(model=display)
        self.metrics.prompt_tokens.inc(len(prompt_ids))
        if (
            self.max_transfer_bytes
            and handoff.nbytes() > self.max_transfer_bytes
        ):
            return http._json(
                413,
                {"error": {"message": (
                    f"handoff of {handoff.nbytes()} bytes exceeds the "
                    f"{self.max_transfer_bytes}-byte transfer limit"
                )}},
            )
        hid = (http.headers.get("X-Handoff-Id") or "").strip() or None
        try:
            result = HTTPTransport(
                target, timeout=self.transfer_timeout
            ).send(handoff, handoff_id=hid)
        except TransferError as e:
            logger.warning("handoff push to %s failed: %s", target, e)
            return http._json(502, {"error": {"message": str(e)}})
        self.metrics.kv_transfer_seconds.observe(
            result.seconds, direction="export"
        )
        return http._json(
            200,
            {
                "object": "kv.handoff",
                "handoff_id": result.handoff_id,
                "decode_addr": target,
                "model": display,
                "prompt_tokens": len(prompt_ids),
                "first_token": handoff.first_token,
                "transfer": {
                    "bytes": result.bytes,
                    "seconds": round(result.seconds, 6),
                },
            },
        )

    def _handle_kv_import(self, http):
        """POST /v1/kv/import — receive a serialized handoff (chunked
        upload) into the bounded handoff store; the follow-up generate
        request references it via X-Disagg-Handoff."""
        from kubeai_tpu.disagg.handoff import HandoffError, deserialize
        from kubeai_tpu.disagg.transport import (
            TransferError,
            read_chunked_body,
        )

        if self.role == "prefill":
            return http._json(
                400,
                {"error": {"message":
                           "prefill-role engines do not accept handoffs"}},
            )
        if self._draining.is_set():
            return self._drain_refusal(http)
        t0 = time.monotonic()
        try:
            te = (http.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                blob = read_chunked_body(
                    http.rfile, max_bytes=self.max_transfer_bytes
                )
            else:
                n = int(http.headers.get("Content-Length", 0) or 0)
                if self.max_transfer_bytes and n > self.max_transfer_bytes:
                    raise TransferError(
                        f"upload of {n} bytes exceeds the "
                        f"{self.max_transfer_bytes}-byte transfer limit"
                    )
                blob = http.rfile.read(n) if n else b""
        except TransferError as e:
            http.close_connection = True  # unread body bytes may remain
            return http._json(413, {"error": {"message": str(e)}})
        try:
            handoff = deserialize(blob)
        except HandoffError as e:
            return http._json(400, {"error": {"message": str(e)}})
        hid = self._handoffs.put(
            handoff, (http.headers.get("X-Handoff-Id") or "").strip() or None
        )
        seconds = time.monotonic() - t0
        # Bytes are counted at engine import time (disagg_stats via
        # sync_engine) so in-process and HTTP transfers land in the same
        # counter; only the receive latency is observed here.
        self.metrics.kv_transfer_seconds.observe(seconds, direction="import")
        return http._json(
            200, {"handoff_id": hid, "bytes": len(blob)}
        )

    # -- cluster KV-sharing tier -----------------------------------------------

    def kv_holdings(self) -> list[str]:
        """Held page-hash chains (hex) for /v1/state, empty when sharing
        is off (no point shipping the table to the aggregator then)."""
        if not self.kv_sharing:
            return []
        inner = getattr(self.engine, "inner", self.engine)
        holdings = getattr(inner, "prefix_holdings", None)
        return holdings() if holdings is not None else []

    def _handle_kv_export(self, http, body: dict):
        """POST /v1/kv/export — serve a peer's partial-chain prefix fetch:
        JSON {"prefix_hashes": [hex...], "max_bytes": N} in, a KVP1 page
        blob out (possibly empty when nothing of the chain is held). The
        transfer cap is the tighter of the caller's max_bytes and this
        server's own transfer limit."""
        from kubeai_tpu.disagg.handoff import serialize_pages

        if not self.kv_sharing:
            return http._json(
                404, {"error": {"message": "KV sharing is not enabled"}}
            )
        if self._draining.is_set():
            return self._drain_refusal(http)
        hashes = body.get("prefix_hashes")
        if not isinstance(hashes, list) or not all(
            isinstance(h, str) for h in hashes
        ):
            return http._json(
                400,
                {"error": {"message": "prefix_hashes must be a hex list"}},
            )
        max_bytes = body.get("max_bytes", 0)
        if isinstance(max_bytes, bool) or not isinstance(max_bytes, int):
            max_bytes = 0
        cap = max(0, max_bytes)
        if self.max_transfer_bytes:
            cap = (
                min(cap, self.max_transfer_bytes)
                if cap else self.max_transfer_bytes
            )
        inner = getattr(self.engine, "inner", self.engine)
        export_fn = getattr(inner, "export_prefix_pages", None)
        export = export_fn(hashes, cap) if export_fn is not None else None
        if export is None:
            return http._json(
                400,
                {"error": {"message": (
                    "prefix export unavailable (paged prefix cache off "
                    "or malformed chain)"
                )}},
            )
        blob = serialize_pages(export)
        http._last_status = 200
        http.send_response(200)
        http.send_header("Content-Type", "application/octet-stream")
        http.send_header("Content-Length", str(len(blob)))
        http.send_header("X-KV-Pages", str(export.n_pages))
        http.end_headers()
        http.wfile.write(blob)

    def _maybe_fetch_prefix(
        self, headers, prompt_ids: list[int], deadline_ms: int
    ) -> None:
        """Best-effort prefix KV fetch before admission: compute the
        prompt's chain, and when a peer (X-KV-Source, supplied by the
        router only for closed-circuit holders) or the objstore spill
        store holds pages past the local cached depth, pull and seed them
        so admission's ordinary prefix-hit path skips that prefill.
        Unconditional-fallback contract: every failure path returns
        silently and the request recomputes — this method can cost
        latency (bounded by the deadline budget and kv_fetch_timeout)
        but never correctness."""
        import http.client as _hc

        from kubeai_tpu.disagg.handoff import (
            HandoffError,
            deserialize_pages,
        )

        inner = getattr(self.engine, "inner", self.engine)
        compute = getattr(inner, "compute_prefix_chain", None)
        depth_fn = getattr(inner, "cached_prefix_depth", None)
        import_fn = getattr(inner, "import_prefix_pages", None)
        if compute is None or depth_fn is None or import_fn is None:
            return
        t0 = time.monotonic()
        # deadline_ms is None when deadline admission is off entirely.
        budget_s = (
            deadline_ms / 1000.0 if deadline_ms and deadline_ms > 0 else None
        )

        def budget_left() -> float | None:
            if budget_s is None:
                return None
            return budget_s - (time.monotonic() - t0)

        try:
            chain = compute(prompt_ids)
        except Exception:
            return
        # Mirror admission's hit cap: pages past it can never be adopted
        # (the final token must compute its own logits), so fetching them
        # would be pure transfer waste.
        ps = self.engine.cfg.page_size
        chain = chain[: max(0, (len(prompt_ids) - 1) // ps)]
        if not chain:
            return
        depth = depth_fn(chain)
        if depth >= len(chain):
            return  # full local hit; nothing to fetch
        missing = chain[depth:]
        source = (headers.get("X-KV-Source") or "").strip()
        if source:
            left = budget_left()
            if left is not None and left <= 0:
                return
            self.metrics.kv_fetch_attempts.inc(source="peer")
            timeout = self.kv_fetch_timeout
            if left is not None:
                timeout = min(timeout, left)
            conn = None
            try:
                payload = json.dumps(
                    {
                        "prefix_hashes": missing,
                        "max_bytes": self.max_transfer_bytes,
                    }
                ).encode()
                conn = _hc.HTTPConnection(source, timeout=timeout)
                conn.request(
                    "POST", "/v1/kv/export", body=payload,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    raise OSError(f"peer answered {resp.status}")
                blob = resp.read()
                if (
                    self.max_transfer_bytes
                    and len(blob) > self.max_transfer_bytes
                ):
                    raise OSError(
                        f"peer blob of {len(blob)} bytes exceeds the "
                        f"{self.max_transfer_bytes}-byte transfer limit"
                    )
                left = budget_left()
                if left is not None and left <= 0:
                    raise OSError("deadline budget exhausted mid-fetch")
                export = deserialize_pages(blob)
                n = import_fn(export, source="peer")
                if n > 0:
                    self.metrics.kv_fetch_bytes.inc(len(blob))
                    return
            except Exception as e:
                # Broad by contract: a peer dying MID-TRANSFER surfaces
                # as http.client.IncompleteRead (an HTTPException, not an
                # OSError) and a corrupt blob as HandoffError — all of it
                # must degrade to recompute, never fail the request.
                logger.warning("peer KV fetch from %s failed: %s", source, e)
                self.metrics.kv_fetch_failures.inc(source="peer")
            finally:
                if conn is not None:
                    conn.close()
        if self.kv_spill is None:
            return
        # Objstore fill: single-page blobs keyed by chain hash, imported
        # one page at a time so a partial fill still shortens prefill.
        filled = 0
        self.metrics.kv_fetch_attempts.inc(source="spill")
        for h in missing:
            left = budget_left()
            if left is not None and left <= 0:
                break
            try:
                blob = self.kv_spill.get(h)
            except Exception:
                blob = None
            if blob is None:
                break  # chain must stay consecutive; stop at first miss
            try:
                export = deserialize_pages(blob)
                if import_fn(export, source="spill") == 0:
                    break
            except (HandoffError, ValueError):
                break
            filled += 1
            self.metrics.kv_fetch_bytes.inc(len(blob))
        if filled == 0:
            self.metrics.kv_fetch_failures.inc(source="spill")

    def _handle_decode_from_handoff(self, http, body: dict, chat: bool, hid: str):
        """Decode role: admit a previously imported handoff straight into
        a slot (no prefill graph runs) and stream from its first decode
        step. The handoff's first token was sampled by the prefill
        engine — it is emitted here as the stream's first event."""
        from kubeai_tpu.disagg.handoff import HandoffError
        from kubeai_tpu.engine.engine import EngineBusy

        handoff = self._handoffs.pop(hid)
        if handoff is None:
            return http._json(
                404,
                {"error": {"message": f"unknown handoff id {hid!r} "
                           "(expired or already consumed)"}},
            )
        display = handoff.model or self.served_model_name
        sp = SamplingParams(
            temperature=handoff.temperature,
            top_k=handoff.top_k,
            top_p=handoff.top_p,
            max_tokens=handoff.max_tokens,
            seed=handoff.seed,
            stop=tuple(handoff.stop),
        )
        sub: queue.Queue = queue.Queue()

        def register(rid: int) -> None:
            with self._sub_lock:
                self._subscribers[rid] = sub

        try:
            rid, first_ev = self.engine.import_handoff(
                handoff, on_admit=register
            )
        except EngineBusy as e:
            return self._shed_response(http, str(e))
        except EngineDraining:
            return self._drain_refusal(http)
        except KeyError as e:
            return http._json(404, {"error": {"message": str(e)}})
        except HandoffError as e:
            return http._json(400, {"error": {"message": str(e)}})
        sub.put(first_ev)
        self.metrics.requests_total.inc(model=display)
        self.metrics.active_requests.inc()
        self.metrics.prompt_tokens.inc(handoff.plen)
        self._work.set()
        stream = bool(body.get("stream", False))
        t0 = time.monotonic()
        span = getattr(http, "current_span", None)
        reqs = [(rid, sub, sp)]
        try:
            if stream:
                self._stream_response(http, reqs, display, chat, t0=t0,
                                      span=span)
            else:
                self._unary_response(http, reqs, display, chat, handoff.plen)
        finally:
            if span is not None and not span.end_ns:
                span.set_attribute(
                    "request.duration_s", time.monotonic() - t0
                )
                span.set_attribute("disagg.handoff_id", hid)
            self.engine.cancel(rid)
            with self._sub_lock:
                self._subscribers.pop(rid, None)
            self.metrics.active_requests.dec()

    def _shed_response(self, http, message: str, retry_after: float | None = None):
        """429 with a COMPUTED Retry-After (queue depth ÷ drain rate, from
        the scheduler — never a constant; jittered ONCE through the
        shared helper so header and body carry the same value) and
        per-class queue depths in the body, so clients and the LB can
        make informed retry decisions."""
        sched = self._scheduler()
        if retry_after is None:
            retry_after = sched.retry_after() if sched is not None else 1.0
        retry_after = retryafter.jittered(retry_after)
        depths = sched.class_depths() if sched is not None else {}
        return http._json(
            429,
            {
                "error": {"message": message},
                "queue": {
                    "depths": depths,
                    "retry_after_s": round(retry_after, 3),
                },
            },
            headers={"Retry-After": retryafter.format_header(retry_after)},
        )

    def _collect(self, rid, sub, sp, on_delta=None, deadline=None,
                 resume_tokens=(), resume_emitted=None):
        """Drain tokens; detokenize incrementally; apply stop strings.
        Returns (text, finish_reason, n_completion_tokens).

        request_timeout is a TOTAL budget for the request, not a per-token
        gap — a slow drip must not hold a batch slot indefinitely. With
        n > 1 the caller passes ONE deadline shared by every choice so
        the whole HTTP request stays inside a single budget.

        Continuation: `resume_tokens` seeds the token buffer so stop
        strings and detokenization see the FULL completion, while
        on_delta only fires past `resume_emitted` characters (what the
        dead stream already delivered to the client — defaults to the
        whole resumed text). on_delta receives (delta_text, new_tokens):
        the tokens consumed since its previous call, which streaming
        chunks expose as `token_ids` so the proxy can resume THIS stream
        too if it dies."""
        tokens: list[int] = list(resume_tokens)
        sent_tokens = len(tokens)
        if tokens:
            base_text = self.tokenizer.decode(tokens)
            emitted_len = (
                len(base_text) if resume_emitted is None
                else max(0, min(int(resume_emitted), len(base_text)))
            )
        else:
            emitted_len = 0
        finish = "length"
        if deadline is None:
            deadline = time.monotonic() + self.request_timeout
        while True:
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise queue.Empty
                ev = sub.get(timeout=remaining)
            except queue.Empty:
                # Stalled engine or abandoned stream: stop decoding now —
                # otherwise the request keeps a batch slot to max_tokens.
                self.engine.cancel(rid)
                finish = "timeout"
                break
            if ev.token < 0:
                # Drain-kill sentinel: the drain budget expired; end this
                # stream cleanly with whatever was generated so far.
                self.engine.cancel(rid)
                finish = "timeout"
                break
            tokens.append(ev.token)
            self.metrics.generated_tokens.inc()
            text = self.tokenizer.decode(tokens)
            # Stop strings act on detokenized text (engine core is
            # token-space only; see sampling.SamplingParams docstring).
            stop_hit = None
            for s in sp.stop:
                idx = text.find(s, max(0, emitted_len - len(s)))
                if idx != -1:
                    stop_hit = idx
                    break
            if stop_hit is not None:
                if on_delta and stop_hit > emitted_len:
                    on_delta(text[emitted_len:stop_hit],
                             tokens[sent_tokens:])
                    sent_tokens = len(tokens)
                self.engine.cancel(rid)
                return text[:stop_hit], "stop", len(tokens)
            if on_delta and len(text) > emitted_len:
                # Hold back a partial UTF-8 replacement char at the tail.
                safe = text[:-1] if text.endswith("�") else text
                if len(safe) > emitted_len:
                    on_delta(safe[emitted_len:], tokens[sent_tokens:])
                    sent_tokens = len(tokens)
                    emitted_len = len(safe)
            if ev.finished:
                finish = ev.finish_reason or "stop"
                break
        text = self.tokenizer.decode(tokens)
        if on_delta and len(text) > emitted_len:
            on_delta(text[emitted_len:], tokens[sent_tokens:])
        return text, finish, len(tokens)

    def _unary_response(self, http, reqs, display, chat, n_prompt,
                        resume_tokens=(), resume_emitted=None):
        # Usage counts the tokens actually generated (re-encoding the text
        # diverges around merges/special tokens and from the
        # generated_tokens metric). Choices decode CONCURRENTLY in the
        # engine; draining them in index order is fine — later choices'
        # events buffer in their queues meanwhile.
        choices = []
        total_completion = 0
        any_timeout = False
        deadline = time.monotonic() + self.request_timeout
        for i, (rid, sub, sp_i) in enumerate(reqs):
            text, finish, completion_tokens = self._collect(
                rid, sub, sp_i, deadline=deadline,
                resume_tokens=resume_tokens if i == 0 else (),
                resume_emitted=resume_emitted if i == 0 else None,
            )
            if finish == "timeout":
                any_timeout = True
                finish = "length"  # partial result; valid OpenAI value
            total_completion += completion_tokens
            if chat:
                choices.append(
                    {
                        "index": i,
                        "message": {"role": "assistant", "content": text},
                        "finish_reason": finish,
                    }
                )
            else:
                choices.append(
                    {"index": i, "text": text, "finish_reason": finish}
                )
        if any_timeout and total_completion == 0:
            # No choice produced a single token within the budget —
            # stalled OR merely backlogged; either way this replica can't
            # serve it now. 503 (not 500) so the proxy retries a
            # different replica (nothing is on the wire yet in unary).
            # Retry-After from scheduler state (shared helper), not a
            # constant: a backlogged replica's hint should reflect its
            # queue.
            sched = self._scheduler()
            ra = retryafter.jittered(
                sched.retry_after() if sched is not None else 1.0
            )
            return http._json(
                503,
                {"error": {"message": "engine produced no tokens within "
                           f"{self.request_timeout}s"}},
                headers={"Retry-After": retryafter.format_header(ra)},
            )
        usage = {
            "prompt_tokens": n_prompt,
            "completion_tokens": total_completion,
            "total_tokens": n_prompt + total_completion,
        }
        payload = {
            "id": f"cmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion" if chat else "text_completion",
            "created": int(time.time()),
            "model": display,
            "choices": choices,
            "usage": usage,
        }
        http._json(200, payload)

    def _stream_response(self, http, reqs, display, chat, t0=None, span=None,
                         resume_tokens=(), resume_emitted=None):
        """SSE stream. With n > 1 the choices stream SEQUENTIALLY in index
        order (each chunk carries its index, which is all the protocol
        requires); later choices decode concurrently and buffer while an
        earlier one streams.

        Every content chunk carries a top-level `token_ids` field — the
        raw tokens behind its delta — which OpenAI clients ignore and
        the routing proxy accumulates so it can resume the stream as a
        continuation request when this replica dies mid-generation."""
        http.send_response(200)
        http.send_header("Content-Type", "text/event-stream")
        http.send_header("Cache-Control", "no-cache")
        http.send_header("Transfer-Encoding", "chunked")
        http.end_headers()
        rid_s = f"cmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())

        def send_chunk(obj: dict):
            data = f"data: {json.dumps(obj)}\n\n".encode()
            http.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            http.wfile.flush()

        def send_choice(choice: dict, token_ids=()):
            send_chunk(
                {
                    "id": rid_s,
                    "object": (
                        "chat.completion.chunk" if chat else "text_completion"
                    ),
                    "created": created,
                    "model": display,
                    "choices": [choice],
                    **(
                        {"token_ids": [int(t) for t in token_ids]}
                        if token_ids else {}
                    ),
                }
            )

        deadline = time.monotonic() + self.request_timeout
        ttft_seen = [False]
        for i, (rid, sub, sp_i) in enumerate(reqs):

            def on_delta(delta_text: str, new_tokens=(), _i=i):
                if not ttft_seen[0]:
                    ttft_seen[0] = True
                    if span is not None and t0 is not None:
                        span.set_attribute(
                            "request.ttft_s", time.monotonic() - t0
                        )
                if chat:
                    send_choice(
                        {
                            "index": _i,
                            "delta": {"content": delta_text},
                            "finish_reason": None,
                        },
                        token_ids=new_tokens,
                    )
                else:
                    send_choice(
                        {"index": _i, "text": delta_text,
                         "finish_reason": None},
                        token_ids=new_tokens,
                    )

            _text, finish, _n = self._collect(
                rid, sub, sp_i, on_delta=on_delta, deadline=deadline,
                resume_tokens=resume_tokens if i == 0 else (),
                resume_emitted=resume_emitted if i == 0 else None,
            )
            if finish == "timeout":
                # Headers are already on the wire; the best we can do is a
                # valid finish value on the final chunk.
                finish = "length"
            send_choice(
                {"index": i, "delta": {}, "finish_reason": finish}
                if chat
                else {"index": i, "text": "", "finish_reason": finish}
            )
        done = b"data: [DONE]\n\n"
        http.wfile.write(f"{len(done):x}\r\n".encode() + done + b"\r\n")
        http.wfile.write(b"0\r\n\r\n")
        http.wfile.flush()

    # -- embeddings (TextEmbedding feature) -------------------------------------

    def _handle_embeddings(self, http, body: dict):
        if getattr(self.engine, "is_lockstep", False):
            # The embed jit is a separate computation host 0 would enter
            # alone — on a multi-host slice that deadlocks the mesh.
            return http._json(
                400,
                {"error": {"message":
                           "embeddings not supported on multi-host replicas"}},
            )
        fam = self.engine.family
        if getattr(fam, "hidden_states", None) is None:
            return http._json(
                400,
                {"error": {"message": f"model family {fam.name} has no embedding support"}},
            )
        inputs = body.get("input", "")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not inputs or not all(isinstance(i, str) for i in inputs):
            return http._json(
                400, {"error": {"message": "input must be a string or list of strings"}}
            )
        import jax.numpy as jnp
        import numpy as np

        ids = [self.tokenizer.encode(t) or [0] for t in inputs]
        max_len = self.engine.cfg.max_seq_len
        if any(len(i) > max_len for i in ids):
            return http._json(400, {"error": {"message": "input too long"}})
        bucket = self.engine._bucket(max(len(i) for i in ids))
        batch = np.zeros((len(ids), bucket), np.int32)
        for row, i in enumerate(ids):
            batch[row, : len(i)] = i
        lengths = jnp.asarray([len(i) for i in ids], jnp.int32)
        vecs = np.asarray(
            self._embed_jit(self.engine.params, jnp.asarray(batch), lengths)
        )
        total_tokens = int(sum(len(i) for i in ids))
        self.metrics.prompt_tokens.inc(total_tokens)
        return http._json(
            200,
            {
                "object": "list",
                "model": self.served_model_name,
                "data": [
                    {
                        "object": "embedding",
                        "index": i,
                        "embedding": [float(x) for x in vecs[i]],
                    }
                    for i in range(len(ids))
                ],
                "usage": {
                    "prompt_tokens": total_tokens,
                    "total_tokens": total_tokens,
                },
            },
        )

    @property
    def _embed_jit(self):
        if not hasattr(self, "_embed_jit_cached"):
            import jax

            fam, mcfg = self.engine.family, self.engine.model_cfg
            self._embed_jit_cached = jax.jit(
                lambda params, tokens, lengths: fam.hidden_states(
                    params, mcfg, tokens, lengths
                )
            )
        return self._embed_jit_cached

    # -- adapter admin ----------------------------------------------------------

    def _handle_load_adapter(self, http, body: dict):
        name = body.get("lora_name")
        if not name:
            return http._json(400, {"error": {"message": "lora_name required"}})
        path_or_url = body.get("lora_path") or body.get("lora_url") or ""
        if name in self.engine.loaded_adapters():
            # Idempotent only for the SAME source: a changed path/url means
            # the adapter was updated (the operator re-sends on URL-hash
            # change) and must actually reload — short-circuiting here
            # would silently keep serving stale weights forever.
            if self._adapter_sources.get(name) == path_or_url:
                return http._json(
                    200, {"status": "already loaded", "lora_name": name}
                )
            if self.engine.adapter_in_use(name):
                # A reload would be refused after the (possibly large)
                # weight download; answer the 409 before fetching. The
                # engine's own guard re-checks authoritatively.
                return http._json(409, {"error": {"message": (
                    f"adapter {name!r} has in-flight requests; retry "
                    "after they finish"
                )}})
        try:
            if self.adapter_fetcher is not None:
                weights = self.adapter_fetcher(name, path_or_url)
            else:
                from kubeai_tpu.engine.lora_weights import load_peft_adapter

                weights = load_peft_adapter(
                    path_or_url, self.engine.model_cfg,
                    max_rank=self.engine.cfg.max_lora_rank,
                )
            self.engine.load_adapter(name, weights)
        except RuntimeError as e:
            if "in-flight" in str(e):
                # Reload refused while requests decode with the old
                # version; the operator's backoff requeue retries.
                return http._json(409, {"error": {"message": str(e)}})
            logger.exception("adapter load failed")
            return http._json(400, {"error": {"message": str(e)}})
        except Exception as e:
            logger.exception("adapter load failed")
            return http._json(400, {"error": {"message": str(e)}})
        self._adapter_sources[name] = path_or_url
        return http._json(200, {"status": "loaded", "lora_name": name})

    def _handle_unload_adapter(self, http, body: dict):
        name = body.get("lora_name")
        if not name:
            return http._json(400, {"error": {"message": "lora_name required"}})
        try:
            ok = self.engine.unload_adapter(name)
        except RuntimeError as e:
            # In-flight requests still decode with this adapter; the
            # caller (operator adapter reconcile) retries after drain.
            return http._json(409, {"error": {"message": str(e)}})
        if ok:
            self._adapter_sources.pop(name, None)
            return http._json(200, {"status": "unloaded", "lora_name": name})
        return http._json(404, {"error": {"message": f"adapter {name} not found"}})


# ---- process entrypoint ------------------------------------------------------


class _WorkerHealthServer:
    """Minimal /health endpoint for multi-host WORKER processes."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8000):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = b'{"status": "ok", "role": "worker"}'
                status = 200 if self.path == "/health" else 404
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = DeepBacklogHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        if self._thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeai-tpu-engine")
    ap.add_argument("--model-url", required=True)
    ap.add_argument("--served-model-name", default="model")
    ap.add_argument("--model-dir", default="", help="pre-downloaded cache dir")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--tpu-topology", default="")
    # Multi-host slices (v5e-4x4 and larger span hosts): every host runs
    # this server process; JAX's distributed runtime wires them into one
    # mesh over DCN for init + ICI for collectives. On GKE these come from
    # the TPU podslice environment (reference parity: the operator treats a
    # replica as one Pod; a multi-host replica is one Pod per host behind
    # the same headless service).
    ap.add_argument("--dcn-coordinator", default=os.environ.get("TPU_COORDINATOR", ""),
                    help="host:port of process 0 (enables jax.distributed)")
    ap.add_argument("--process-id", type=int,
                    default=int(os.environ.get("TPU_PROCESS_ID", "0")))
    ap.add_argument("--num-processes", type=int,
                    default=int(os.environ.get("TPU_PROCESS_COUNT", "1")))
    ap.add_argument("--num-slots", type=int, default=32)
    ap.add_argument("--max-seq-len", type=int, default=4096)
    ap.add_argument("--max-adapters", type=int, default=4)
    ap.add_argument("--decode-chunk", type=int, default=8)
    ap.add_argument("--quantization", default="", choices=["", "int8"])
    ap.add_argument(
        "--kv-dtype", default="", choices=["", "bfloat16", "int8"],
        help="paged KV-cache storage dtype; int8 stores quantized pages "
        "with per-token-per-head scales (~2x slot capacity at equal "
        "HBM, half the KV bytes on every handoff/fetch/spill) "
        "(CRD kvCache.dtype)",
    )
    ap.add_argument(
        "--pipeline", action="store_true",
        help="legacy alias for --step-overlap on (overlap decode chunks "
        "with host processing; direct PJRT targets)",
    )
    ap.add_argument(
        "--step-overlap", choices=["auto", "on", "off"], default="auto",
        help="overlapped step pipeline: dispatch decode chunk N+1 before "
        "reaping chunk N so readback/admission/detokenize/SSE hide "
        "behind device compute (token-identical to the synchronous "
        "loop). auto = on wherever the topology allows (off for "
        "lockstep multihost and pipeline parallelism); on = require it "
        "(typed error where unsupported) (CRD engineStep.overlap)",
    )
    ap.add_argument(
        "--speculate", type=int, default=0,
        help="speculative-decoding window (0 = off); prompt-lookup "
        "proposals unless --draft-url provides a draft model",
    )
    ap.add_argument(
        "--spec-adaptive", choices=["on", "off"], default="on",
        help="measure speculative vs chunk decode and run the faster",
    )
    ap.add_argument(
        "--draft-url", default="",
        help="small SAME-FAMILY draft model whose chain proposes the "
        "speculative window (requires --speculate > 0); any model URL "
        "scheme --model-url accepts",
    )
    ap.add_argument(
        "--draft-dir", default="", help="pre-downloaded draft cache dir"
    )
    ap.add_argument(
        "--prefill-chunk", type=int, default=0,
        help="chunked prefill size (0 = whole-prompt bucketed prefill); "
        "one compiled graph for every prompt length",
    )
    ap.add_argument(
        "--default-priority", default="standard",
        choices=list(PRIORITY_CLASSES),
        help="priority class for requests without an X-Priority header "
        "(CRD scheduling.defaultPriority)",
    )
    ap.add_argument(
        "--max-deadline-ms", type=int, default=0,
        help="cap on client X-Deadline-Ms values, and the default "
        "deadline when none is sent; 0 disables deadline admission "
        "(CRD scheduling.maxDeadlineMs)",
    )
    ap.add_argument(
        "--queue-shares", default="",
        help="per-class dispatch shares guaranteeing lower bands a "
        "fraction of admissions under sustained higher-priority load, "
        "e.g. 'standard=0.3,batch=0.05' (CRD scheduling.queueShares)",
    )
    ap.add_argument(
        "--max-queue", type=int, default=256,
        help="pending-queue depth past which requests are shed with 429 "
        "and a computed Retry-After",
    )
    ap.add_argument(
        "--drain-timeout", type=float, default=30.0,
        help="graceful-drain budget in seconds: after SIGTERM or POST "
        "/v1/drain, in-flight generations get this long to finish "
        "before being terminated (CRD spec.drainTimeoutSeconds)",
    )
    ap.add_argument(
        "--watchdog-timeout", type=float, default=120.0,
        help="step-watchdog budget in seconds: with work active and no "
        "engine step progress for this long, /health flips and the "
        "process exits nonzero so Kubernetes restarts the pod "
        "(system config resilience.watchdogTimeout); 0 disables",
    )
    ap.add_argument(
        "--role", default="unified",
        choices=["unified", "prefill", "decode"],
        help="disaggregated serving role: prefill engines run chunked "
        "prefill and push a KV handoff to the decode pool instead of "
        "entering decode; decode engines admit handoffs directly into "
        "slots (POST /v1/kv/import + X-Disagg-Handoff), bypassing the "
        "prefill graphs (CRD spec.disaggregation)",
    )
    ap.add_argument(
        "--max-transfer-mb", type=int, default=0,
        help="cap on one serialized KV handoff (0 = unlimited); uploads "
        "and exports past it answer 413 "
        "(CRD disaggregation.maxTransferMB)",
    )
    ap.add_argument(
        "--transfer-timeout", type=float, default=30.0,
        help="prefill-role push budget toward the decode pool's "
        "/v1/kv/import (CRD disaggregation.transferTimeoutSeconds)",
    )
    ap.add_argument(
        "--prefix-cache", action="store_true",
        help="automatic prefix caching: shared prompt prefixes skip "
        "prefill (pairs with the router's PrefixHash affinity). Implies "
        "a prefill chunk of min(512, max-seq-len/4) when unset — the "
        "adoptable prefix is capped at max-seq-len minus the chunk, so "
        "the chunk must stay well under the context",
    )
    ap.add_argument(
        "--kv-sharing", action="store_true",
        help="cluster-shared prefix/KV tier: publish held page-hash "
        "chains via /v1/state, serve peer page exports on "
        "/v1/kv/export, and pull common-prefix pages from the "
        "X-KV-Source peer before prefill; implies --prefix-cache "
        "(holdings live in the paged prefix cache) "
        "(CRD spec.kvSharing)",
    )
    ap.add_argument(
        "--kv-fetch-timeout", type=float, default=5.0,
        help="budget for one peer KV-page fetch "
        "(CRD kvSharing.fetchTimeoutSeconds)",
    )
    ap.add_argument(
        "--kv-spill-url", default="",
        help="object-store URL evicted idle KV pages spill to and are "
        "re-filled from; empty = in-memory spill "
        "(CRD kvSharing.spillURL)",
    )
    ap.add_argument(
        "--snapshot-url", default="",
        help="object-store URL for engine boot snapshots (post-conversion "
        "param tree + XLA compilation cache, keyed by model/config/mesh "
        "fingerprint): boot restores from it when a matching snapshot "
        "exists and writes one back on the first full-load boot; empty "
        "disables (CRD coldStart.snapshotURL)",
    )
    ap.add_argument(
        "--snapshot-dir", default="",
        help="local staging dir for snapshot fetch/publish and the "
        "persistent compilation cache (default: a fresh temp dir)",
    )
    ap.add_argument(
        "--snapshot-no-publish", action="store_true",
        help="restore-only consumer: never write a snapshot back after "
        "a full-load boot (CRD coldStart.publish=false)",
    )
    args = ap.parse_args(argv)
    if args.kv_sharing:
        args.prefix_cache = True
    if args.prefix_cache and args.prefill_chunk <= 0:
        args.prefill_chunk = max(32, min(512, args.max_seq_len // 4))
    if args.num_processes > 1:
        # Lockstep multihost: every host must replay the SAME op/step
        # sequence; an overlapped reap would reorder host 0's broadcast
        # schedule relative to the workers'. Refuse an explicit "on"
        # (typed — the operator asked for something this topology cannot
        # do), auto-off otherwise — BEFORE EngineConfig is built, so the
        # worker hosts' engines resolve identically to host 0's.
        from kubeai_tpu.engine.engine import StepOverlapUnsupported

        if args.step_overlap == "on" or args.pipeline:
            raise StepOverlapUnsupported(
                "--step-overlap on does not compose with lockstep "
                "multihost (--num-processes > 1): the overlapped reap "
                "would desynchronize the per-step cross-host broadcast; "
                "use --step-overlap auto or off"
            )
        args.step_overlap = "off"

    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("kubeai-tpu-engine")

    if args.dcn_coordinator and args.num_processes > 1:
        import jax

        jax.distributed.initialize(
            coordinator_address=args.dcn_coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        log.info(
            "joined distributed runtime: process %d/%d via %s",
            args.process_id, args.num_processes, args.dcn_coordinator,
        )

    from kubeai_tpu.engine.weights import (
        load_hf_config,
        load_llama_params,
        resolve_model_dir,
    )
    from kubeai_tpu.models.registry import get_model_family
    from kubeai_tpu.parallel.mesh import mesh_from_topology, single_device_mesh

    model_dir = resolve_model_dir(args.model_url, args.model_dir)
    hf_cfg = load_hf_config(model_dir)
    arch = (hf_cfg.get("architectures") or ["LlamaForCausalLM"])[0]
    family = get_model_family(arch)
    model_cfg = family.config_from_hf(hf_cfg)
    log.info("loading %s (%s) from %s", args.served_model_name, arch, model_dir)

    if family.feature == "SpeechToText":
        from kubeai_tpu.engine.weights import load_params
        from kubeai_tpu.engine.whisper_server import TranscriptionServer

        params = load_params(family.name, model_dir, model_cfg)
        try:
            from transformers import AutoTokenizer

            wtok = AutoTokenizer.from_pretrained(model_dir)
        except Exception:
            wtok = None
        tserver = TranscriptionServer(
            params, model_cfg, args.served_model_name,
            tokenizer=wtok, host=args.host, port=args.port,
        )
        tserver.start()
        log.info("transcription engine serving on %s:%d", args.host, tserver.port)
        try:
            while True:
                time.sleep(5)
        except KeyboardInterrupt:
            tserver.stop()
        return 0

    from kubeai_tpu.engine.coldstart import ColdStartManager
    from kubeai_tpu.engine.weights import load_params as _load_params

    # The mesh comes first now: its shape is part of the snapshot
    # fingerprint (a tree sharded for a different slice must miss).
    mesh = (
        mesh_from_topology(args.tpu_topology)
        if args.tpu_topology
        else single_device_mesh()
    )

    engine_cfg = EngineConfig(
        num_slots=args.num_slots,
        max_seq_len=args.max_seq_len,
        # LoRA is lockstep on multihost: host 0 broadcasts adapter
        # weights to every process (engine/multihost.py).
        max_adapters=args.max_adapters,
        decode_chunk=args.decode_chunk,
        pipeline=args.pipeline,
        step_overlap=args.step_overlap,
        quantization=args.quantization,
        kv_dtype=args.kv_dtype,
        speculate=args.speculate,
        spec_adaptive=args.spec_adaptive == "on",
        prefill_chunk=args.prefill_chunk,
        prefix_cache=args.prefix_cache,
    )

    # Restore-first boot: a complete snapshot under this (model, config,
    # mesh) fingerprint skips HF conversion — and its bundled compilation
    # cache makes the first jit a cache read. Absence/mismatch falls back
    # to the full load path unchanged.
    coldstart = ColdStartManager(
        args.snapshot_url,
        args.served_model_name,
        engine_cfg,
        mesh,
        work_dir=args.snapshot_dir or None,
        publish=not args.snapshot_no_publish,
    )
    params = coldstart.acquire_params(
        lambda: _load_params(family.name, model_dir, model_cfg)
    )

    draft = None
    if args.draft_url:
        if args.speculate <= 0:
            raise SystemExit("--draft-url requires --speculate > 0")
        draft_dir = resolve_model_dir(args.draft_url, args.draft_dir)
        draft_hf = load_hf_config(draft_dir)
        draft_arch = (draft_hf.get("architectures") or [arch])[0]
        if get_model_family(draft_arch) is not family:
            raise SystemExit(
                f"draft model family ({draft_arch}) must match the "
                f"target's ({arch})"
            )
        draft_cfg = family.config_from_hf(draft_hf)
        draft = (draft_cfg, _load_params(family.name, draft_dir, draft_cfg))
        log.info("loaded draft model (%s) from %s", draft_arch, draft_dir)

    from kubeai_tpu.objstore import KVSpillStore
    from kubeai_tpu.scheduling import RequestScheduler, SchedulingPolicy

    shares: dict[str, float] = {}
    if args.queue_shares:
        for pair in args.queue_shares.split(","):
            pair = pair.strip()
            if not pair:
                continue
            cls, _, share = pair.partition("=")
            shares[cls.strip()] = float(share)
    scheduler = RequestScheduler(
        SchedulingPolicy(
            default_priority=args.default_priority,
            queue_shares=shares,
            max_deadline_ms=args.max_deadline_ms,
        )
    )

    tokenizer = load_tokenizer(model_dir)
    multihost = args.num_processes > 1
    engine = Engine(
        family,
        model_cfg,
        params,
        mesh=mesh,
        cfg=engine_cfg,
        eos_token_ids=tuple(getattr(tokenizer, "eos_token_ids", ())),
        draft=draft,
        scheduler=scheduler,
    )

    if multihost and args.process_id != 0:
        # WORKER host: mirror host 0's ops/steps in lockstep; expose only
        # /health so kubelet probes see the process (never the OpenAI
        # surface — the LB routes to host 0 alone).
        from kubeai_tpu.engine.multihost import worker_loop

        health = _WorkerHealthServer(host=args.host, port=args.port)
        health.start()
        log.info(
            "worker %d/%d: health on %s:%d, entering lockstep loop",
            args.process_id, args.num_processes, args.host, health.port,
        )
        worker_loop(engine)
        health.stop()
        return 0

    if multihost:
        from kubeai_tpu.engine.multihost import LockstepEngine

        engine = LockstepEngine(engine)

    # Warm-up before Ready: compile prefill+decode so the first request
    # doesn't eat compile time (the reference warms Ollama the same way —
    # reference: engine_ollama.go:173-213 probe warm-up). In multihost
    # mode this is the first lockstep broadcast: workers join here.
    # Phase-split for the cold-start record: the first generate carries
    # the jit (or the persistent-cache read on the restore path), the
    # second measures the warmed steady state.
    with coldstart.tracker.phase("compile"):
        engine.generate(
            [[1, 2, 3]], SamplingParams(temperature=0.0, max_tokens=2)
        )
    with coldstart.tracker.phase("warmup"):
        engine.generate(
            [[1, 2, 3]], SamplingParams(temperature=0.0, max_tokens=2)
        )
    # Write-back on first boot: publish AFTER warm-up so the snapshot
    # ships a compilation cache that already holds the serving graphs.
    coldstart.maybe_publish(params)
    coldstart.tracker.finish()
    log.info(
        "warm-up complete (cold start %.2fs, %s)",
        coldstart.tracker.total_s,
        "restored" if coldstart.tracker.restored else "full load",
    )

    def _watchdog_exit():
        # The watchdog already flipped /health; exiting nonzero hands the
        # pod to kubelet's restart policy — a wedged XLA dispatch cannot
        # be recovered in-process.
        log.error(
            "engine watchdog: hung device step — exiting 3 for restart"
        )
        os._exit(3)

    server = EngineServer(
        engine,
        tokenizer,
        args.served_model_name,
        host=args.host,
        port=args.port,
        max_queue=args.max_queue,
        default_priority=args.default_priority,
        max_deadline_ms=args.max_deadline_ms,
        drain_timeout=args.drain_timeout,
        role=args.role,
        max_transfer_mb=args.max_transfer_mb,
        transfer_timeout=args.transfer_timeout,
        watchdog_timeout=args.watchdog_timeout,
        watchdog_action=_watchdog_exit,
        kv_sharing=args.kv_sharing,
        kv_fetch_timeout=args.kv_fetch_timeout,
        kv_spill_store=(
            KVSpillStore(args.kv_spill_url) if args.kv_sharing else None
        ),
        cold_start=coldstart.tracker.snapshot(),
    )
    tracing.configure(service_name=f"kubeai-tpu-engine.{args.served_model_name}")
    server.start()
    log.info("engine serving on %s:%d", args.host, server.port)

    # SIGTERM (pod deletion / rollout) triggers the graceful drain: stop
    # admitting, flip /health so the LB ejects us, finish in-flight work
    # within --drain-timeout, then exit. The renderer sets
    # terminationGracePeriodSeconds above this budget so kubelet's KILL
    # never races the drain.
    import signal

    exit_evt = threading.Event()

    def _drain_and_exit():
        server.begin_drain()
        server.wait_drained()
        exit_evt.set()

    def _on_sigterm(signum, frame):
        log.info("SIGTERM: draining (budget %.1fs)", args.drain_timeout)
        threading.Thread(target=_drain_and_exit, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded/test use)
    try:
        while not exit_evt.wait(timeout=0.5):
            pass
    except KeyboardInterrupt:
        pass
    server.stop()
    if multihost:
        engine.shutdown()  # release the workers
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
