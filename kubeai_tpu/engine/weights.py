"""Checkpoint loading: HuggingFace-format directories → native param trees.

The reference delegates weight loading to engine images + a loader
container (reference: components/model-loader/load.sh, engine_vllm.go
runai-streamer args). Here loading is native AND streamed:

  - Tensors are read LAZILY: safetensors headers are parsed once, each
    tensor is seek-read from its shard file only when its target slot is
    being filled, and stacked-layer leaves are assembled directly into
    preallocated TARGET-dtype (bf16) buffers. Peak host memory is the
    bf16 param tree plus ONE tensor — never an fp32 full-model staging
    copy (SURVEY.md §7 "sharded load fast enough for elastic scaling";
    70B in fp32 staging would need ~280 GB host RAM).
  - Remote artifacts (s3:// gs:// oss://) stream shard-at-a-time to
    local disk through kubeai_tpu.objstore (chunked object→file copies,
    one object in flight), then lazy-load from there.

Supported sources:
  - local directory (pvc:// mounts, cache dirs): config.json + *.safetensors
  - hf://repo: resolved through HF_HOME cache / huggingface_hub when
    network is available (zero-egress test environments use local dirs)
  - s3://, gs://, oss:// bucket prefixes (engine-direct; cache Jobs use
    kubeai_tpu.loader for the shared-PVC flow)
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np


class WeightLoadError(RuntimeError):
    pass


def load_hf_config(model_dir: str) -> dict:
    path = os.path.join(model_dir, "config.json")
    if not os.path.exists(path):
        raise WeightLoadError(f"no config.json under {model_dir}")
    with open(path) as f:
        return json.load(f)


_ST_DTYPES = {
    "F32": np.float32,
    "F16": np.float16,
    "BF16": None,  # handled specially below
    "I64": np.int64,
    "I32": np.int32,
    "U8": np.uint8,
}


def _decode_raw(raw: bytes, dtype_s: str, shape, name: str) -> np.ndarray:
    if dtype_s == "BF16":
        u16 = np.frombuffer(raw, np.uint16)
        u32 = u16.astype(np.uint32) << 16
        return u32.view(np.float32).reshape(shape)
    np_dtype = _ST_DTYPES.get(dtype_s)
    if np_dtype is None:
        raise WeightLoadError(f"unsupported dtype {dtype_s} for {name}")
    return np.frombuffer(raw, np_dtype).reshape(shape)


class LazyTensors:
    """Lazy tensor mapping over a checkpoint directory.

    safetensors: headers parsed up front (cheap), tensor data seek-read
    on demand — nothing resident until requested, nothing cached after.
    pytorch_model*.bin: eager fallback (torch pickles don't support
    random access without loading)."""

    def __init__(self, model_dir: str):
        self._index: dict[str, tuple[str, str, list, int, int]] = {}
        self._eager: dict[str, np.ndarray] | None = None
        st_files = sorted(
            f for f in os.listdir(model_dir) if f.endswith(".safetensors")
        )
        if st_files:
            for fname in st_files:
                fpath = os.path.join(model_dir, fname)
                with open(fpath, "rb") as f:
                    header_len = int.from_bytes(f.read(8), "little")
                    header = json.loads(f.read(header_len))
                    base = 8 + header_len
                for name, meta in header.items():
                    if name == "__metadata__":
                        continue
                    start, end = meta["data_offsets"]
                    self._index[name] = (
                        fpath, meta["dtype"], meta["shape"],
                        base + start, end - start,
                    )
            return
        bin_files = sorted(
            f for f in os.listdir(model_dir)
            if f.endswith(".bin") and f.startswith("pytorch_model")
        )
        if not bin_files:
            raise WeightLoadError(
                f"no safetensors or pytorch_model*.bin in {model_dir}"
            )
        import torch

        self._eager = {}
        for fname in bin_files:
            sd = torch.load(
                os.path.join(model_dir, fname), map_location="cpu",
                weights_only=True,
            )
            for k, v in sd.items():
                self._eager[k] = v.to(torch.float32).numpy()

    def __contains__(self, name: str) -> bool:
        if self._eager is not None:
            return name in self._eager
        return name in self._index

    def keys(self):
        return (self._eager or self._index).keys()

    def __getitem__(self, name: str) -> np.ndarray:
        """fp32 view of one tensor, freshly read (caller must not expect
        the buffer to persist cheaply — copy into the target and drop)."""
        if self._eager is not None:
            return self._eager[name]
        if name not in self._index:
            raise KeyError(name)
        fpath, dtype_s, shape, offset, nbytes = self._index[name]
        with open(fpath, "rb") as f:
            f.seek(offset)
            raw = f.read(nbytes)
        a = _decode_raw(raw, dtype_s, shape, name)
        return np.asarray(a, np.float32)


def _stream_helpers(model_dir: str, NL: int, dtype):
    """(tensors, get, stack, leaf): the shared streamed-assembly kit.

    `stack` fills a preallocated [NL, ...] TARGET-dtype buffer one layer
    tensor at a time (numpy casts on assignment), so the fp32 view of
    each tensor lives only for its own copy — peak host memory is the
    target tree + one tensor, not an fp32 full model."""
    t = LazyTensors(model_dir)
    target = np.dtype(dtype)

    def get(name: str) -> np.ndarray:
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return t[name]

    def leaf(name: str) -> jnp.ndarray:
        return jnp.asarray(get(name).astype(target))

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        buf = None
        for i in range(NL):
            a = get(fmt.format(i=i))
            if transpose:
                a = a.T
            if buf is None:
                buf = np.empty((NL, *a.shape), target)
            buf[i] = a  # casts fp32 -> target in place
        return jnp.asarray(buf)

    return t, get, stack, leaf


def load_llama_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """Map a HF LlamaForCausalLM checkpoint onto the stacked-layer tree
    (kubeai_tpu.models.llama.param_specs layout).

    HF stores per-layer `model.layers.{i}.self_attn.q_proj.weight` with
    shape [out, in]; our layout stacks layers and keeps [in, out] so the
    forward einsums contract without transposes on the MXU.
    """
    t, get, stack, leaf = _stream_helpers(model_dir, cfg.num_layers, dtype)

    extra_layers = {}
    if getattr(cfg, "attention_bias", False):
        extra_layers = {
            "bq": stack("model.layers.{i}.self_attn.q_proj.bias", transpose=False),
            "bk": stack("model.layers.{i}.self_attn.k_proj.bias", transpose=False),
            "bv": stack("model.layers.{i}.self_attn.v_proj.bias", transpose=False),
        }
    params = {
        "embed": leaf("model.embed_tokens.weight"),
        "layers": {
            "input_norm": stack(
                "model.layers.{i}.input_layernorm.weight", transpose=False
            ),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
            "post_attn_norm": stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                transpose=False,
            ),
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
            **extra_layers,
        },
        "final_norm": leaf("model.norm.weight"),
    }
    if "lm_head.weight" in t:
        params["lm_head"] = leaf("lm_head.weight")
    else:  # tied embeddings
        params["lm_head"] = params["embed"]
    return params


def resolve_model_dir(model_url: str, model_dir: str = "") -> str:
    """Resolve a Model URL to a local directory.

    pvc://name/path → /model/path (the operator mounts the PVC at /model);
    hf://repo → huggingface_hub snapshot (network) or $HF_HOME cache;
    plain paths pass through. `model_dir` (the cache dir) wins when set.
    """
    if model_dir:
        return model_dir
    if model_url.startswith("pvc://"):
        ref = model_url[len("pvc://"):]
        sub = ref.split("/", 1)[1] if "/" in ref else ""
        return os.path.join("/model", sub) if sub else "/model"
    if model_url.startswith("hf://"):
        repo = model_url[len("hf://"):].split("?")[0]
        try:
            from huggingface_hub import snapshot_download

            return snapshot_download(repo)
        except Exception as e:
            raise WeightLoadError(
                f"cannot download {repo} (offline?): {e}"
            ) from e
    if model_url.split("://")[0] in ("s3", "gs", "oss"):
        # Engine-direct object-store load: stream shard files one at a
        # time to a local cache dir (disk, chunked — never whole-model in
        # RAM), then lazy-read from there. Cache Jobs pre-populate a PVC
        # via kubeai_tpu.loader for the shared-filesystem flow.
        import hashlib as _hashlib

        from kubeai_tpu import objstore

        cache_root = os.environ.get(
            "KUBEAI_WEIGHTS_CACHE", "/tmp/kubeai-weights"
        )
        digest = _hashlib.sha256(model_url.encode()).hexdigest()[:16]
        dest = os.path.join(cache_root, digest)
        done_marker = os.path.join(dest, ".kubeai-complete")
        if not os.path.exists(done_marker):
            # Download into a process-private staging dir, then atomically
            # rename: concurrent replicas sharing the cache never read a
            # half-written shard, and the loser of the rename race just
            # uses the winner's copy.
            import shutil as _shutil
            import tempfile as _tempfile

            os.makedirs(cache_root, exist_ok=True)
            staging = _tempfile.mkdtemp(dir=cache_root, prefix=f".{digest}-")
            try:
                objstore.download_prefix(model_url.split("?")[0], staging)
                with open(os.path.join(staging, ".kubeai-complete"), "w") as f:
                    f.write(model_url)
                try:
                    os.rename(staging, dest)
                except OSError:
                    if not os.path.exists(done_marker):
                        raise
            finally:
                if os.path.exists(staging):
                    _shutil.rmtree(staging, ignore_errors=True)
        return dest
    if os.path.isdir(model_url):
        return model_url
    raise WeightLoadError(f"unsupported model url {model_url!r}")


def load_gemma_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """HF Gemma/Gemma2 checkpoint → kubeai_tpu.models.gemma layout."""
    t, get, stack, leaf = _stream_helpers(model_dir, cfg.num_layers, dtype)

    layers = {
        "input_norm": stack("model.layers.{i}.input_layernorm.weight", False),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
        "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
        "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
        "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
    }
    if cfg.sandwich_norms:  # gemma2 naming
        layers["post_attn_norm"] = stack(
            "model.layers.{i}.post_attention_layernorm.weight", False
        )
        layers["pre_mlp_norm"] = stack(
            "model.layers.{i}.pre_feedforward_layernorm.weight", False
        )
        layers["post_mlp_norm"] = stack(
            "model.layers.{i}.post_feedforward_layernorm.weight", False
        )
    else:  # gemma1: post_attention_layernorm IS the pre-MLP norm
        layers["pre_mlp_norm"] = stack(
            "model.layers.{i}.post_attention_layernorm.weight", False
        )
    return {
        "embed": leaf("model.embed_tokens.weight"),
        "layers": layers,
        "final_norm": leaf("model.norm.weight"),
    }


def load_mixtral_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """HF Mixtral checkpoint → kubeai_tpu.models.mixtral layout
    (experts stacked: w1=gate, w3=up, w2=down)."""
    NL, X = cfg.num_layers, cfg.num_experts
    t, get, stack, leaf = _stream_helpers(model_dir, NL, dtype)
    target = np.dtype(dtype)

    def stack_experts(w_name):
        buf = None
        for i in range(NL):
            for e in range(X):
                a = get(
                    f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"
                ).T
                if buf is None:
                    buf = np.empty((NL, X, *a.shape), target)
                buf[i, e] = a
        return jnp.asarray(buf)  # [NL, X, in, out]

    return {
        "embed": leaf("model.embed_tokens.weight"),
        "layers": {
            "input_norm": stack("model.layers.{i}.input_layernorm.weight", False),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
            "post_attn_norm": stack(
                "model.layers.{i}.post_attention_layernorm.weight", False
            ),
            "router": stack("model.layers.{i}.block_sparse_moe.gate.weight"),
            "w_gate": stack_experts("w1"),
            "w_up": stack_experts("w3"),
            "w_down": stack_experts("w2"),
        },
        "final_norm": leaf("model.norm.weight"),
        "lm_head": leaf("lm_head.weight"),
    }


_LOADERS = {
    "llama": load_llama_params,
    "qwen": load_llama_params,  # same layout + biases (attention_bias)
    "gemma": load_gemma_params,
    "mixtral": load_mixtral_params,
}


def load_params(family_name: str, model_dir: str, cfg, dtype=jnp.bfloat16):
    """Family-dispatching checkpoint loader."""
    if family_name not in _LOADERS:
        raise WeightLoadError(f"no weight loader for family {family_name!r}")
    return _LOADERS[family_name](model_dir, cfg, dtype)


def load_whisper_params(model_dir: str, cfg, dtype=jnp.float32) -> dict:
    """HF WhisperForConditionalGeneration → kubeai_tpu.models.whisper layout."""
    t = LazyTensors(model_dir)

    def get(name):
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return t[name]

    def j(a):
        return jnp.asarray(a, dtype)

    def attn(prefix):
        return {
            "wq": j(get(f"{prefix}.q_proj.weight").T),
            "bq": j(get(f"{prefix}.q_proj.bias")),
            "wk": j(get(f"{prefix}.k_proj.weight").T),
            "wv": j(get(f"{prefix}.v_proj.weight").T),
            "bv": j(get(f"{prefix}.v_proj.bias")),
            "wo": j(get(f"{prefix}.out_proj.weight").T),
            "bo": j(get(f"{prefix}.out_proj.bias")),
        }

    def ln(name):
        return {"w": j(get(f"{name}.weight")), "b": j(get(f"{name}.bias"))}

    def ffn(prefix):
        return {
            "w1": j(get(f"{prefix}.fc1.weight").T),
            "b1": j(get(f"{prefix}.fc1.bias")),
            "w2": j(get(f"{prefix}.fc2.weight").T),
            "b2": j(get(f"{prefix}.fc2.bias")),
        }

    enc_layers = [
        {
            "ln1": ln(f"model.encoder.layers.{i}.self_attn_layer_norm"),
            "attn": attn(f"model.encoder.layers.{i}.self_attn"),
            "ln2": ln(f"model.encoder.layers.{i}.final_layer_norm"),
            "ffn": ffn(f"model.encoder.layers.{i}"),
        }
        for i in range(cfg.encoder_layers)
    ]
    dec_layers = [
        {
            "ln1": ln(f"model.decoder.layers.{i}.self_attn_layer_norm"),
            "self_attn": attn(f"model.decoder.layers.{i}.self_attn"),
            "ln2": ln(f"model.decoder.layers.{i}.encoder_attn_layer_norm"),
            "cross_attn": attn(f"model.decoder.layers.{i}.encoder_attn"),
            "ln3": ln(f"model.decoder.layers.{i}.final_layer_norm"),
            "ffn": ffn(f"model.decoder.layers.{i}"),
        }
        for i in range(cfg.decoder_layers)
    ]
    return {
        # torch conv1d weight [out, in, k] -> [k, in, out]
        "conv1_w": j(get("model.encoder.conv1.weight").transpose(2, 1, 0)),
        "conv1_b": j(get("model.encoder.conv1.bias")),
        "conv2_w": j(get("model.encoder.conv2.weight").transpose(2, 1, 0)),
        "conv2_b": j(get("model.encoder.conv2.bias")),
        "enc_pos": j(get("model.encoder.embed_positions.weight")),
        "enc_layers": enc_layers,
        "enc_ln": ln("model.encoder.layer_norm"),
        "dec_embed": j(get("model.decoder.embed_tokens.weight")),
        "dec_pos": j(get("model.decoder.embed_positions.weight")),
        "dec_layers": dec_layers,
        "dec_ln": ln("model.decoder.layer_norm"),
    }


_LOADERS["whisper"] = load_whisper_params


# ---- native checkpoint format (orbax) ---------------------------------------
#
# Engine-side save/resume (SURVEY.md §5.4: the reference has no engine-side
# checkpointing — weight loading is delegated to vLLM images; here the
# engine can snapshot its post-conversion param tree so replica restarts
# skip the HF->native mapping and load sharded directly from disk/GCS-fuse).


def save_native_checkpoint(path: str, params) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=True)
        ckptr.wait_until_finished()


def load_native_checkpoint(path: str, like=None):
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(os.path.abspath(path), like)
        return ckptr.restore(os.path.abspath(path))
