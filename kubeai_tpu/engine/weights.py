"""Checkpoint loading: HuggingFace-format directories → native param trees.

The reference delegates weight loading to engine images + a loader
container (reference: components/model-loader/load.sh, engine_vllm.go
runai-streamer args). Here loading is native: safetensors/PyTorch-bin
checkpoints are mapped tensor-by-tensor onto the stacked-layer layout and
device_put with the target sharding — each shard's slice streams straight
from host to its device (no full-model host copy per device).

Supported sources:
  - local directory (pvc:// mounts, cache dirs): config.json + *.safetensors
  - hf://repo: resolved through HF_HOME cache / huggingface_hub when
    network is available (zero-egress test environments use local dirs)
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np


class WeightLoadError(RuntimeError):
    pass


def load_hf_config(model_dir: str) -> dict:
    path = os.path.join(model_dir, "config.json")
    if not os.path.exists(path):
        raise WeightLoadError(f"no config.json under {model_dir}")
    with open(path) as f:
        return json.load(f)


def _open_checkpoint_tensors(model_dir: str) -> dict[str, np.ndarray]:
    """Load all tensors from safetensors (preferred) or torch .bin files."""
    tensors: dict[str, np.ndarray] = {}
    st_files = sorted(
        f for f in os.listdir(model_dir) if f.endswith(".safetensors")
    )
    if st_files:
        try:
            from safetensors import safe_open
        except ImportError:
            safe_open = None
        for fname in st_files:
            fpath = os.path.join(model_dir, fname)
            if safe_open is not None:
                with safe_open(fpath, framework="np") as f:
                    for k in f.keys():
                        tensors[k] = f.get_tensor(k)
            else:
                tensors.update(_read_safetensors_raw(fpath))
        return tensors
    bin_files = sorted(
        f for f in os.listdir(model_dir)
        if f.endswith(".bin") and f.startswith("pytorch_model")
    )
    if bin_files:
        import torch

        for fname in bin_files:
            sd = torch.load(
                os.path.join(model_dir, fname), map_location="cpu",
                weights_only=True,
            )
            for k, v in sd.items():
                tensors[k] = v.to(torch.float32).numpy()
        return tensors
    raise WeightLoadError(f"no safetensors or pytorch_model*.bin in {model_dir}")


_ST_DTYPES = {
    "F32": np.float32,
    "F16": np.float16,
    "BF16": None,  # handled specially below
    "I64": np.int64,
    "I32": np.int32,
    "U8": np.uint8,
}


def _read_safetensors_raw(path: str) -> dict[str, np.ndarray]:
    """Minimal safetensors reader (header + raw slices) — no dependency."""
    out: dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        header_len = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(header_len))
        base = 8 + header_len
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            dtype_s = meta["dtype"]
            start, end = meta["data_offsets"]
            f.seek(base + start)
            raw = f.read(end - start)
            shape = meta["shape"]
            if dtype_s == "BF16":
                u16 = np.frombuffer(raw, np.uint16).reshape(shape)
                u32 = u16.astype(np.uint32) << 16
                out[name] = u32.view(np.float32).reshape(shape)
            else:
                np_dtype = _ST_DTYPES.get(dtype_s)
                if np_dtype is None:
                    raise WeightLoadError(f"unsupported dtype {dtype_s} for {name}")
                out[name] = np.frombuffer(raw, np_dtype).reshape(shape)
    return out


def load_llama_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """Map a HF LlamaForCausalLM checkpoint onto the stacked-layer tree
    (kubeai_tpu.models.llama.param_specs layout).

    HF stores per-layer `model.layers.{i}.self_attn.q_proj.weight` with
    shape [out, in]; our layout stacks layers and keeps [in, out] so the
    forward einsums contract without transposes on the MXU.
    """
    t = _open_checkpoint_tensors(model_dir)
    NL = cfg.num_layers

    def get(name: str) -> np.ndarray:
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return np.asarray(t[name], np.float32)

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        arrs = []
        for i in range(NL):
            a = get(fmt.format(i=i))
            arrs.append(a.T if transpose else a)
        return jnp.asarray(np.stack(arrs), dtype)

    extra_layers = {}
    if getattr(cfg, "attention_bias", False):
        extra_layers = {
            "bq": stack("model.layers.{i}.self_attn.q_proj.bias", transpose=False),
            "bk": stack("model.layers.{i}.self_attn.k_proj.bias", transpose=False),
            "bv": stack("model.layers.{i}.self_attn.v_proj.bias", transpose=False),
        }
    embed = get("model.embed_tokens.weight")
    params = {
        "embed": jnp.asarray(embed, dtype),
        "layers": {
            "input_norm": stack(
                "model.layers.{i}.input_layernorm.weight", transpose=False
            ),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
            "post_attn_norm": stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                transpose=False,
            ),
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
            **extra_layers,
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if "lm_head.weight" in t:
        params["lm_head"] = jnp.asarray(get("lm_head.weight"), dtype)
    else:  # tied embeddings
        params["lm_head"] = params["embed"]
    return params


def resolve_model_dir(model_url: str, model_dir: str = "") -> str:
    """Resolve a Model URL to a local directory.

    pvc://name/path → /model/path (the operator mounts the PVC at /model);
    hf://repo → huggingface_hub snapshot (network) or $HF_HOME cache;
    plain paths pass through. `model_dir` (the cache dir) wins when set.
    """
    if model_dir:
        return model_dir
    if model_url.startswith("pvc://"):
        ref = model_url[len("pvc://"):]
        sub = ref.split("/", 1)[1] if "/" in ref else ""
        return os.path.join("/model", sub) if sub else "/model"
    if model_url.startswith("hf://"):
        repo = model_url[len("hf://"):].split("?")[0]
        try:
            from huggingface_hub import snapshot_download

            return snapshot_download(repo)
        except Exception as e:
            raise WeightLoadError(
                f"cannot download {repo} (offline?): {e}"
            ) from e
    if os.path.isdir(model_url):
        return model_url
    raise WeightLoadError(f"unsupported model url {model_url!r}")


def load_gemma_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """HF Gemma/Gemma2 checkpoint → kubeai_tpu.models.gemma layout."""
    t = _open_checkpoint_tensors(model_dir)
    NL = cfg.num_layers

    def get(name):
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return np.asarray(t[name], np.float32)

    def stack(fmt, transpose=True):
        return jnp.asarray(
            np.stack([
                get(fmt.format(i=i)).T if transpose else get(fmt.format(i=i))
                for i in range(NL)
            ]),
            dtype,
        )

    layers = {
        "input_norm": stack("model.layers.{i}.input_layernorm.weight", False),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
        "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
        "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
        "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
    }
    if cfg.sandwich_norms:  # gemma2 naming
        layers["post_attn_norm"] = stack(
            "model.layers.{i}.post_attention_layernorm.weight", False
        )
        layers["pre_mlp_norm"] = stack(
            "model.layers.{i}.pre_feedforward_layernorm.weight", False
        )
        layers["post_mlp_norm"] = stack(
            "model.layers.{i}.post_feedforward_layernorm.weight", False
        )
    else:  # gemma1: post_attention_layernorm IS the pre-MLP norm
        layers["pre_mlp_norm"] = stack(
            "model.layers.{i}.post_attention_layernorm.weight", False
        )
    return {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }


def load_mixtral_params(model_dir: str, cfg, dtype=jnp.bfloat16) -> dict:
    """HF Mixtral checkpoint → kubeai_tpu.models.mixtral layout
    (experts stacked: w1=gate, w3=up, w2=down)."""
    t = _open_checkpoint_tensors(model_dir)
    NL, X = cfg.num_layers, cfg.num_experts

    def get(name):
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return np.asarray(t[name], np.float32)

    def stack(fmt, transpose=True):
        return jnp.asarray(
            np.stack([
                get(fmt.format(i=i)).T if transpose else get(fmt.format(i=i))
                for i in range(NL)
            ]),
            dtype,
        )

    def stack_experts(w_name):
        out = []
        for i in range(NL):
            per_layer = [
                get(
                    f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"
                ).T
                for e in range(X)
            ]
            out.append(np.stack(per_layer))
        return jnp.asarray(np.stack(out), dtype)  # [NL, X, in, out]

    return {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": {
            "input_norm": stack("model.layers.{i}.input_layernorm.weight", False),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
            "post_attn_norm": stack(
                "model.layers.{i}.post_attention_layernorm.weight", False
            ),
            "router": stack("model.layers.{i}.block_sparse_moe.gate.weight"),
            "w_gate": stack_experts("w1"),
            "w_up": stack_experts("w3"),
            "w_down": stack_experts("w2"),
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
        "lm_head": jnp.asarray(get("lm_head.weight"), dtype),
    }


_LOADERS = {
    "llama": load_llama_params,
    "qwen": load_llama_params,  # same layout + biases (attention_bias)
    "gemma": load_gemma_params,
    "mixtral": load_mixtral_params,
}


def load_params(family_name: str, model_dir: str, cfg, dtype=jnp.bfloat16):
    """Family-dispatching checkpoint loader."""
    if family_name not in _LOADERS:
        raise WeightLoadError(f"no weight loader for family {family_name!r}")
    return _LOADERS[family_name](model_dir, cfg, dtype)


def load_whisper_params(model_dir: str, cfg, dtype=jnp.float32) -> dict:
    """HF WhisperForConditionalGeneration → kubeai_tpu.models.whisper layout."""
    t = _open_checkpoint_tensors(model_dir)

    def get(name):
        if name not in t:
            raise WeightLoadError(f"missing tensor {name}")
        return np.asarray(t[name], np.float32)

    def j(a):
        return jnp.asarray(a, dtype)

    def attn(prefix):
        return {
            "wq": j(get(f"{prefix}.q_proj.weight").T),
            "bq": j(get(f"{prefix}.q_proj.bias")),
            "wk": j(get(f"{prefix}.k_proj.weight").T),
            "wv": j(get(f"{prefix}.v_proj.weight").T),
            "bv": j(get(f"{prefix}.v_proj.bias")),
            "wo": j(get(f"{prefix}.out_proj.weight").T),
            "bo": j(get(f"{prefix}.out_proj.bias")),
        }

    def ln(name):
        return {"w": j(get(f"{name}.weight")), "b": j(get(f"{name}.bias"))}

    def ffn(prefix):
        return {
            "w1": j(get(f"{prefix}.fc1.weight").T),
            "b1": j(get(f"{prefix}.fc1.bias")),
            "w2": j(get(f"{prefix}.fc2.weight").T),
            "b2": j(get(f"{prefix}.fc2.bias")),
        }

    enc_layers = [
        {
            "ln1": ln(f"model.encoder.layers.{i}.self_attn_layer_norm"),
            "attn": attn(f"model.encoder.layers.{i}.self_attn"),
            "ln2": ln(f"model.encoder.layers.{i}.final_layer_norm"),
            "ffn": ffn(f"model.encoder.layers.{i}"),
        }
        for i in range(cfg.encoder_layers)
    ]
    dec_layers = [
        {
            "ln1": ln(f"model.decoder.layers.{i}.self_attn_layer_norm"),
            "self_attn": attn(f"model.decoder.layers.{i}.self_attn"),
            "ln2": ln(f"model.decoder.layers.{i}.encoder_attn_layer_norm"),
            "cross_attn": attn(f"model.decoder.layers.{i}.encoder_attn"),
            "ln3": ln(f"model.decoder.layers.{i}.final_layer_norm"),
            "ffn": ffn(f"model.decoder.layers.{i}"),
        }
        for i in range(cfg.decoder_layers)
    ]
    return {
        # torch conv1d weight [out, in, k] -> [k, in, out]
        "conv1_w": j(get("model.encoder.conv1.weight").transpose(2, 1, 0)),
        "conv1_b": j(get("model.encoder.conv1.bias")),
        "conv2_w": j(get("model.encoder.conv2.weight").transpose(2, 1, 0)),
        "conv2_b": j(get("model.encoder.conv2.bias")),
        "enc_pos": j(get("model.encoder.embed_positions.weight")),
        "enc_layers": enc_layers,
        "enc_ln": ln("model.encoder.layer_norm"),
        "dec_embed": j(get("model.decoder.embed_tokens.weight")),
        "dec_pos": j(get("model.decoder.embed_positions.weight")),
        "dec_layers": dec_layers,
        "dec_ln": ln("model.decoder.layer_norm"),
    }


_LOADERS["whisper"] = load_whisper_params


# ---- native checkpoint format (orbax) ---------------------------------------
#
# Engine-side save/resume (SURVEY.md §5.4: the reference has no engine-side
# checkpointing — weight loading is delegated to vLLM images; here the
# engine can snapshot its post-conversion param tree so replica restarts
# skip the HF->native mapping and load sharded directly from disk/GCS-fuse).


def save_native_checkpoint(path: str, params) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=True)
        ckptr.wait_until_finished()


def load_native_checkpoint(path: str, like=None):
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(os.path.abspath(path), like)
        return ckptr.restore(os.path.abspath(path))
