"""Multi-host lockstep serving: one engine program, N processes.

JAX multi-controller SPMD requires EVERY process to enter the same jitted
computation in the same order. Requests, however, arrive only at host 0
(the operator exposes only host 0 to the LB). The bridge is op
BROADCAST: host 0 buffers control ops (admissions, cancels), and each
step() broadcasts a fixed-shape descriptor to all processes via
`multihost_utils.broadcast_one_to_all` (itself a collective every
process enters — workers block there until host 0 acts). All processes
then apply the SAME ops to their local Engine replica and run the SAME
engine.step(): the jitted collectives line up across the slice.

Determinism requirements this module enforces:
  - request ids: all processes call inner.add_request in broadcast
    order, so rid sequences match;
  - sampling seeds: resolved ON HOST 0 (explicit seed or drawn once) and
    shipped in the descriptor — never derived from per-process entropy;
  - page allocation (paged cache): the allocator is a deterministic
    free-list, so identical op streams yield identical block tables on
    every host.

LoRA hot-swap IS lockstep: host 0's admin call broadcasts a control
descriptor carrying the op + adapter name, then (for loads) one
fixed-shape weight payload — adapter A/B matrices zero-padded to
max_lora_rank, so every adapter broadcasts with identical shapes and
the zero padding contributes nothing to the delta. Every process then
installs the same weights into the same buffer slot (slot assignment is
deterministic under identical op order). The broadcast happens INSIDE
load_adapter under the same I/O lock step() holds across its
descriptor→tokens→engine.step() sequence, so the global collective
order stays identical on every process.

The serving analog is JetStream/MaxText-style multihost orchestration;
the reference has no counterpart (one-Pod-per-replica,
pod_plan.go:28-156 — engine-internal distribution lives in vLLM images).
"""

from __future__ import annotations

import dataclasses
import logging
import threading

import numpy as np

from kubeai_tpu.engine.engine import Engine, StepEvent
from kubeai_tpu.engine.sampling import SamplingParams

logger = logging.getLogger(__name__)

MAX_ADMITS = 8  # ops per step (excess stays buffered for the next step)
MAX_CANCELS = 32
# meta columns: plen, seed(int32 bit-cast), top_k, adapter_idx, max_tokens
_META_COLS = 5


@dataclasses.dataclass
class _PendingAdd:
    vrid: int  # the virtual rid handed to the caller
    tokens: list[int]
    params: SamplingParams
    adapter_idx: int = 0
    # Name kept alongside the resolved index so unload_adapter can refuse
    # while this admission is still buffered (the index must stay valid
    # until it broadcasts).
    adapter_name: str | None = None
    cancelled: bool = False


# header[4] adapter op codes
_ADAPTER_NONE, _ADAPTER_LOAD, _ADAPTER_UNLOAD = 0, 1, 2
_ADAPTER_NAME_BYTES = 64


def _control_zeros() -> dict:
    """The per-step control descriptor — small (a few hundred bytes), so
    the common no-admission decode step stays cheap on DCN. The padded
    token matrix broadcasts in a SECOND collective only when
    n_admits > 0 (both sides branch on the same header, so the
    collective sequence stays identical across processes); adapter LOAD
    ops likewise trigger a second, fixed-shape weight broadcast."""
    return {
        # n_admits, n_cancels, step, stop, adapter_op
        "header": np.zeros((5,), np.int32),
        "meta": np.zeros((MAX_ADMITS, _META_COLS), np.int32),
        "floats": np.zeros((MAX_ADMITS, 2), np.float32),  # temp, top_p
        "cancels": np.zeros((MAX_CANCELS,), np.int32),
        "adapter_name": np.zeros((_ADAPTER_NAME_BYTES,), np.uint8),
    }


def _encode_name(name: str) -> np.ndarray:
    raw = name.encode("utf-8")
    if len(raw) > _ADAPTER_NAME_BYTES:
        raise ValueError(
            f"adapter name longer than {_ADAPTER_NAME_BYTES} utf-8 bytes"
        )
    buf = np.zeros((_ADAPTER_NAME_BYTES,), np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    return buf


def _decode_name(buf: np.ndarray) -> str:
    return bytes(buf).rstrip(b"\x00").decode("utf-8")


def _lora_payload_zeros(engine: Engine) -> dict:
    """Fixed-shape weight payload: one {target.A/.B} float32 array pair
    per LoRA target, shaped like one buffer slot (rank = max_lora_rank).
    Identical construction on every process ⇒ identical broadcast
    shapes."""
    out = {}
    for target, bufs in engine._lora.items():
        out[target + ".A"] = np.zeros(bufs["A"].shape[1:], np.float32)
        out[target + ".B"] = np.zeros(bufs["B"].shape[1:], np.float32)
    return out


def _payload_to_weights(engine: Engine, payload: dict) -> dict:
    return {
        target: (payload[target + ".A"], payload[target + ".B"])
        for target in engine._lora
    }


def _broadcast(desc, is_source: bool):
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(desc, is_source=is_source)
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    return np.asarray(out)


class LockstepEngine:
    """Engine facade for HOST 0: buffers ops, broadcasts them inside
    step(), and drives the inner engine exactly like every worker drives
    theirs. Exposes the Engine surface EngineServer consumes."""

    is_lockstep = True  # server gates non-lockstep paths (embeddings)

    def __init__(self, inner: Engine):
        from kubeai_tpu.engine.engine import StepOverlapUnsupported

        # Overlapped stepping cannot run under lockstep: every host must
        # replay the SAME op/step sequence, and an unreaped chunk on host
        # 0 would reorder its broadcast schedule relative to the workers'.
        # Explicit "on" (incl. the legacy pipeline bool) is a typed
        # refusal; "auto" silently degrades to the synchronous loop.
        # Defense in depth — server main() resolves this before the
        # worker engines are even built.
        explicit = inner.cfg.step_overlap
        if (
            explicit is True
            or str(explicit).strip().lower() == "on"
            or inner.cfg.pipeline
        ):
            raise StepOverlapUnsupported(
                "step_overlap='on' does not compose with lockstep "
                "multihost: the overlapped reap would desynchronize the "
                "per-step cross-host broadcast; use 'auto' or 'off'"
            )
        inner._overlap = False
        self.inner = inner
        self._lock = threading.Lock()
        # Serializes every broadcast SEQUENCE (a step's descriptor→
        # tokens→engine.step(), an adapter op's descriptor→payload, a
        # shutdown) so the global collective order is identical on every
        # process.
        self._io_lock = threading.RLock()
        self._adds: list[_PendingAdd] = []
        self._cancels: list[int] = []
        # Cancels that raced step(): their admission batch was popped
        # from _adds but its _rid_map entries weren't populated yet.
        # Resolved at the top of the next step().
        self._unresolved_cancels: list[int] = []
        self._next_virtual_rid = 0
        # virtual rid (handed to callers before broadcast) -> inner rid
        self._rid_map: dict[int, int] = {}
        self._entropy = np.random.default_rng()

    # -- Engine surface used by EngineServer ----------------------------------

    @property
    def cfg(self):
        return self.inner.cfg

    @property
    def family(self):
        return self.inner.family

    @property
    def model_cfg(self):
        return self.inner.model_cfg

    @property
    def params(self):
        return self.inner.params

    @property
    def num_pending(self) -> int:
        with self._lock:
            return len(self._adds) + self.inner.num_pending

    @property
    def num_active(self) -> int:
        return self.inner.num_active

    def _bucket(self, n: int) -> int:
        return self.inner._bucket(n)

    def loaded_adapters(self) -> list[str]:
        return self.inner.loaded_adapters()

    def adapter_in_use(self, name: str) -> bool:
        """Engine-surface parity: the server pre-checks this before
        fetching reload weights. Advisory, like Engine.adapter_in_use."""
        return self.inner.adapter_in_use(name)

    def load_adapter(self, name: str, adapter_weights: dict) -> None:
        """Lockstep adapter install: broadcast the op + padded weights to
        every process, then install locally. Synchronous — returns once
        this process has installed (workers install on their own receive,
        strictly before their next engine collective)."""
        if self.inner._lora is None:
            raise ValueError("LoRA is disabled (max_adapters=0)")
        name_buf = _encode_name(name)
        payload = _lora_payload_zeros(self.inner)
        r_max = self.cfg.max_lora_rank
        for target, (A, B) in adapter_weights.items():
            if target + ".A" not in payload:
                raise KeyError(f"unknown LoRA target {target!r}")
            A = np.asarray(A, np.float32)
            B = np.asarray(B, np.float32)
            r = A.shape[-1]
            if r > r_max:
                raise ValueError(f"adapter rank {r} > max_lora_rank {r_max}")
            # Zero-pad rank to r_max: fixed broadcast shapes, and the
            # padding contributes nothing to x@A@B.
            payload[target + ".A"][..., :r] = A
            payload[target + ".B"][:, :r, :] = B
        desc = _control_zeros()
        desc["header"][4] = _ADAPTER_LOAD
        desc["adapter_name"] = name_buf
        with self._io_lock:
            # Capacity must be validated BEFORE any broadcast: a
            # post-broadcast raise would leave workers' loops dead (or
            # diverged) and the next step() collective hanging.
            if (
                name not in self.inner._adapter_slots
                and not self.inner._adapter_free
            ):
                raise RuntimeError(
                    f"adapter capacity ({self.cfg.max_adapters}) exhausted"
                )
            slot = self.inner._adapter_slots.get(name)
            if slot is not None and self.inner._adapter_in_use_locked(slot):
                # Same-name reload would overwrite weights under in-flight
                # streams; refuse BEFORE the broadcast (pre-broadcast
                # mirror of Engine.load_adapter's guard).
                raise RuntimeError(
                    f"adapter {name!r} has in-flight requests; retry "
                    "after they finish"
                )
            _broadcast(desc, is_source=True)
            payload = _broadcast(payload, is_source=True)
            self.inner.load_adapter(
                name, _payload_to_weights(self.inner, payload)
            )

    def unload_adapter(self, name: str) -> bool:
        if self.inner._lora is None or name not in self.inner._adapter_slots:
            return False
        desc = _control_zeros()
        desc["header"][4] = _ADAPTER_UNLOAD
        desc["adapter_name"] = _encode_name(name)
        with self._io_lock:
            # Buffered admissions hold a resolved slot index; unloading
            # now could let a subsequent load reassign that slot to a
            # DIFFERENT adapter before the admission broadcasts —
            # silently decoding with the wrong weights. Refuse instead.
            # _lock is held across the guard AND the broadcast+unload so
            # add_request can't resolve the slot in between; _io_lock is
            # held by step() across its _adds pop, so a popped-but-not-
            # yet-broadcast batch can't slip past the scan either.
            with self._lock:
                if any(
                    a.adapter_name == name and not a.cancelled
                    for a in self._adds
                ):
                    raise RuntimeError(
                        f"adapter {name!r} has queued requests; retry after "
                        "they admit"
                    )
                slot = self.inner._adapter_slots.get(name)
                if slot is not None and self.inner._adapter_in_use_locked(
                    slot
                ):
                    # Pre-broadcast mirror of Engine.unload_adapter's
                    # in-use refusal: raising AFTER the broadcast would
                    # leave every process refusing identically (states
                    # stay consistent) but wastes a collective round.
                    raise RuntimeError(
                        f"adapter {name!r} has in-flight requests; retry "
                        "after they finish"
                    )
                _broadcast(desc, is_source=True)
                return self.inner.unload_adapter(name)

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._adds or self._cancels) or self.inner.has_work()

    def add_request(
        self,
        prompt_tokens: list[int],
        params: SamplingParams | None = None,
        adapter: str | None = None,
        on_admit=None,
        priority: str | None = None,
        client: str = "",
        deadline_ms: float | None = None,
        resume_tokens: list[int] | None = None,
    ) -> int:
        if resume_tokens:
            # Continuation admission would have to replay the resume
            # prefix identically on every host; until the descriptor
            # carries it, multi-host replicas refuse and the proxy falls
            # back to the terminal-error tail.
            raise ValueError(
                "stream resume is not supported on multi-host replicas"
            )
        # Scheduling args are accepted for API parity with Engine but not
        # broadcast: lockstep admission must replay in identical order on
        # every host, so multi-host replicas keep FIFO ordering (every
        # inner scheduler sees the same default-class submissions and WFQ
        # degenerates to arrival order). Queue-full shedding still
        # applies at the HTTP layer; per-class precedence and deadline
        # shedding are single-host features for now.
        params = params or SamplingParams()
        if adapter and self.inner._lora is None:
            raise ValueError("LoRA is disabled (max_adapters=0)")
        if len(prompt_tokens) == 0:
            raise ValueError("empty prompt")
        if len(prompt_tokens) >= self.inner.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt_tokens)} >= max_seq_len "
                f"{self.inner.cfg.max_seq_len}"
            )
        # Seeds ship in the descriptor: resolve host-0-side once, masked
        # to 32 bits (clients may send negative / >32-bit seeds; the
        # inner engine masks too, so the fold-in value stays identical).
        seed = (
            params.seed
            if params.seed is not None
            else int(self._entropy.integers(0, 2**31 - 1))
        )
        params = dataclasses.replace(params, seed=seed & 0xFFFFFFFF)
        with self._lock:
            # Resolve to the inner slot index under _lock so it serializes
            # with unload_adapter (which buys _lock for its entire
            # guard→broadcast→unload sequence): either this admission is
            # appended first (the unload guard sees it and refuses) or the
            # unload completes first (the adapter is gone and we raise).
            # The index is deterministic across processes — identical
            # adapter-op order assigns identical slots; the descriptor
            # ships the index.
            adapter_idx = 0
            if adapter:
                slot = self.inner._adapter_slots.get(adapter)
                if slot is None:
                    raise KeyError(f"adapter {adapter!r} not loaded")
                adapter_idx = slot
            rid = self._next_virtual_rid
            self._next_virtual_rid += 1
            if on_admit is not None:
                # Same contract as Engine.add_request: registration is
                # visible before any step can emit events for this rid.
                on_admit(rid)
            self._adds.append(
                _PendingAdd(
                    rid, list(prompt_tokens), params, adapter_idx,
                    adapter or None,
                )
            )
            return rid

    def cancel(self, rid: int) -> bool:
        with self._lock:
            inner_rid = self._rid_map.pop(rid, None)
            if inner_rid is None:
                # Not yet broadcast: tombstone the buffered entry.
                for add in self._adds:
                    if add.vrid == rid and not add.cancelled:
                        add.cancelled = True
                        return True
                if 0 <= rid < self._next_virtual_rid:
                    # Mid-step race: the admission batch holding this rid
                    # is being broadcast right now (popped from _adds, not
                    # yet in _rid_map) — or the request already finished.
                    # Defer; step() resolves or discards it.
                    self._unresolved_cancels.append(rid)
                    return True
                return False
            # Mapping pruned here: a cancelled request emits no further
            # events (the inner engine releases it on cancel), so keeping
            # the entry would only leak.
            self._cancels.append(inner_rid)
            return True

    def step(self) -> list[StepEvent]:
        """One lockstep iteration: broadcast buffered ops, apply, step.

        _io_lock is held from BEFORE the _adds pop: once an admission
        batch leaves the buffer its resolved adapter indices must stay
        valid until they broadcast, and unload_adapter (which serializes
        on _io_lock) could otherwise free a slot in that window after
        its buffered-admission scan found _adds already empty."""
        with self._io_lock:
            return self._step_locked()

    def _step_locked(self) -> list[StepEvent]:
        with self._lock:
            # Resolve cancels that raced the previous step's broadcast
            # window: by now (single stepping thread) their rids are
            # mapped, back in the buffer, or gone (finished) — gone ones
            # are discarded.
            for vrid in self._unresolved_cancels:
                inner = self._rid_map.pop(vrid, None)
                if inner is not None:
                    self._cancels.append(inner)
                    continue
                for add in self._adds:
                    if add.vrid == vrid and not add.cancelled:
                        add.cancelled = True
                        break
            self._unresolved_cancels = []
            batch = self._adds[:MAX_ADMITS]
            self._adds = self._adds[MAX_ADMITS:]
            cancels = self._cancels[:MAX_CANCELS]
            self._cancels = self._cancels[MAX_CANCELS:]
        live = [a for a in batch if not a.cancelled]
        desc = _control_zeros()
        desc["header"][0] = len(live)
        desc["header"][1] = len(cancels)
        desc["header"][2] = 1  # run a decode step
        for i, add in enumerate(live):
            desc["meta"][i] = [
                len(add.tokens),
                np.uint32(add.params.seed).view(np.int32),
                add.params.top_k,
                add.adapter_idx,
                add.params.max_tokens,
            ]
            desc["floats"][i] = [add.params.temperature, add.params.top_p]
        desc["cancels"][: len(cancels)] = cancels

        with self._io_lock:
            out = _broadcast(desc, is_source=True)
            tokens = None
            if live:  # second, payload-sized collective only on admissions
                tokens = np.zeros(
                    (MAX_ADMITS, self.inner.cfg.max_seq_len), np.int32
                )
                for i, add in enumerate(live):
                    tokens[i, : len(add.tokens)] = add.tokens
                tokens = _broadcast(tokens, is_source=True)
            inner_rids = _apply_descriptor(
                self.inner, out, tokens, do_step=False
            )
            with self._lock:
                for add, inner_rid in zip(live, inner_rids):
                    self._rid_map[add.vrid] = inner_rid
            events = self.inner.step()
        # Map inner rids back to the virtual rids callers hold; prune
        # finished mappings so the table doesn't grow unboundedly.
        with self._lock:
            inv = {v: k for k, v in self._rid_map.items()}
            # Events whose inner rid has no live mapping (cancelled mid
            # step) are DROPPED — falling back to the raw inner rid could
            # deliver tokens to a different request's subscriber once the
            # virtual and inner sequences diverge.
            mapped = [
                StepEvent(inv[ev.rid], ev.token, ev.finished,
                          ev.finish_reason)
                for ev in events
                if ev.rid in inv
            ]
            for ev in events:
                if ev.finished and ev.rid in inv:
                    self._rid_map.pop(inv[ev.rid], None)
        return mapped

    def generate(self, prompts, params=None):
        """Convenience parity with Engine.generate (tests)."""
        outs: dict[int, list[int]] = {}
        rids = [self.add_request(p, params) for p in prompts]
        for r in rids:
            outs[r] = []
        while self.has_work():
            for ev in self.step():
                if ev.rid in outs and ev.token is not None:
                    outs[ev.rid].append(ev.token)
        return [outs[r] for r in rids]

    def shutdown(self) -> None:
        """Release the workers (they exit their loop)."""
        desc = _control_zeros()
        desc["header"][3] = 1
        with self._io_lock:
            _broadcast(desc, is_source=True)


def _apply_descriptor(
    engine: Engine, desc: dict, tokens, do_step: bool
) -> list[int]:
    """Apply a broadcast descriptor to the local engine replica. Returns
    the inner rids assigned to this step's admissions (same on every
    process, by construction)."""
    n_admits = int(desc["header"][0])
    n_cancels = int(desc["header"][1])
    # adapter_idx → name (slot assignment is deterministic, so the map
    # is identical on every process).
    slot_names = (
        {v: k for k, v in engine._adapter_slots.items()}
        if engine._lora is not None
        else {}
    )
    rids = []
    for i in range(n_admits):
        plen, seed_bits, top_k, adapter_idx, max_tokens = (
            int(x) for x in desc["meta"][i]
        )
        temp, top_p = (float(x) for x in desc["floats"][i])
        params = SamplingParams(
            temperature=temp,
            top_k=top_k,
            top_p=top_p,
            max_tokens=max_tokens,
            seed=int(np.int32(seed_bits).view(np.uint32)),
        )
        rids.append(
            engine.add_request(
                list(tokens[i, :plen]), params,
                adapter=slot_names.get(adapter_idx),
            )
        )
    for i in range(n_cancels):
        engine.cancel(int(desc["cancels"][i]))
    if do_step and int(desc["header"][2]):
        engine.step()
    return rids


def worker_loop(engine: Engine) -> None:
    """WORKER processes (process_id > 0): receive descriptors forever,
    mirror host 0's ops and steps. Blocks inside the broadcast collective
    while host 0 is idle."""
    logger.info("multihost worker loop running")
    while True:
        desc = _broadcast(_control_zeros(), is_source=False)
        if int(desc["header"][3]):
            logger.info("multihost worker loop: shutdown")
            return
        adapter_op = int(desc["header"][4])
        if adapter_op == _ADAPTER_LOAD:
            payload = _broadcast(
                _lora_payload_zeros(engine), is_source=False
            )
            # Host 0 validated capacity before broadcasting; a local
            # failure here means state divergence — log loudly but keep
            # the loop alive (a dead worker hangs the whole slice's next
            # collective).
            try:
                engine.load_adapter(
                    _decode_name(desc["adapter_name"]),
                    _payload_to_weights(engine, payload),
                )
            except Exception:
                logger.exception("lockstep adapter load failed on worker")
        elif adapter_op == _ADAPTER_UNLOAD:
            try:
                engine.unload_adapter(_decode_name(desc["adapter_name"]))
            except Exception:
                logger.exception("lockstep adapter unload failed on worker")
        tokens = None
        if int(desc["header"][0]):
            tokens = _broadcast(
                np.zeros((MAX_ADMITS, engine.cfg.max_seq_len), np.int32),
                is_source=False,
            )
        _apply_descriptor(engine, desc, tokens, do_step=True)
